"""Static pipeline verifier — graph checks without running a buffer.

Given an ``nns-launch`` description this builds the link graph from
``pipeline.parse.parse_description`` (pure syntax, no element
construction), consults the static element catalog, and reports
``NNS0xx`` diagnostics: unknown factories/properties (NNS001/002),
duplicate names (NNS003), bad references and pad exhaustion (NNS004),
empty caps intersections (NNS005, computed with ``pipeline/caps.py`` —
the same intersection engine runtime negotiation uses), dangling pads
(NNS006), cycles (NNS007), mux/merge sync-policy conflicts (NNS008), tee
fan-out without queues (NNS009), unmonitorable leaky queues (NNS010),
unknown filter/decoder/converter subplugins (NNS011), and syntax errors
(NNS012).

The same checks that make sense on an already-instantiated graph are
exposed as :func:`verify_pipeline` (behind ``Pipeline.verify()``), so
programmatic pipeline builders get the pre-flight too.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.analysis.catalog import (
    PASSTHROUGH,
    ElementSpec,
    spec_for,
    static_src_caps,
)
from nnstreamer_tpu.analysis.diagnostics import (
    ERROR,
    WARNING,
    Diagnostic,
    Location,
    sort_diagnostics,
)
from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import (
    CONVERTER,
    DECODER,
    FILTER,
    registered_names,
)

#: sync policies accepted by elements/collect.py (kept in sync by tests)
_SYNC_POLICIES = ("nosync", "slowest", "basepad", "refresh")


@dataclasses.dataclass
class _Node:
    """One concrete element occurrence in the description."""

    id: int
    factory: str
    spec: Optional[ElementSpec]
    props: Dict[str, str]               # normalized key -> last value
    prop_positions: List[Tuple[str, str, int]]
    pos: int                            # column of the factory token
    name: Optional[str]                 # explicit name= only
    caps_str: Optional[str] = None      # capsfilter caps token
    out_links: List[int] = dataclasses.field(default_factory=list)
    in_links: List[int] = dataclasses.field(default_factory=list)
    src_used: int = 0
    sink_used: int = 0
    sink_grown: int = 0                 # highest implied sink index + 1

    @property
    def label(self) -> str:
        return self.name or self.factory


def _line_col(text: str, pos: int) -> Tuple[int, int]:
    """0-based absolute offset → 1-based (line, column)."""
    pos = max(0, min(pos, len(text)))
    line = text.count("\n", 0, pos) + 1
    col = pos - (text.rfind("\n", 0, pos) + 1) + 1
    return line, col


class _Verifier:
    def __init__(self, description: str, source: str):
        self.description = description
        self.source = source
        self.diags: List[Diagnostic] = []

    # -- diagnostics ---------------------------------------------------------
    def _loc(self, pos: int) -> Location:
        line, col = _line_col(self.description, pos)
        return Location(self.source, line, col)

    def emit(self, code: str, severity: str, pos: int, message: str,
             hint: Optional[str] = None) -> None:
        self.diags.append(Diagnostic(code, severity, self._loc(pos),
                                     message, hint))

    # -- main ----------------------------------------------------------------
    def run(self) -> List[Diagnostic]:
        from nnstreamer_tpu.pipeline.parse import ParseError, \
            parse_description

        try:
            chains = parse_description(self.description)
        except ParseError as e:
            self.emit("NNS012", ERROR, e.pos or 0, str(e))
            return self.diags
        nodes = self._build_nodes(chains)
        self._check_props(nodes)
        self._check_links(chains, nodes)
        self._check_graph(nodes)
        self._propagate_caps(nodes)
        return sort_diagnostics(self.diags)

    # -- node construction ---------------------------------------------------
    def _build_nodes(self, chains) -> Dict[int, _Node]:
        nodes: Dict[int, _Node] = {}
        self.by_name: Dict[str, _Node] = {}
        self.node_of = {}  # id(LaunchNode) -> _Node for el/caps ast nodes
        for chain in chains:
            for ast in chain:
                if ast.kind in ("ref", "refpad"):
                    continue
                if "=" in (ast.factory or "") and ast.kind == "element":
                    self.emit("NNS012", ERROR, ast.pos,
                              f"property token {ast.factory!r} has no "
                              f"element to apply to")
                    continue
                spec = spec_for(ast.factory)
                if spec is None:
                    self.emit("NNS001", ERROR, ast.pos,
                              f"no such element factory {ast.factory!r}",
                              hint=self._suggest_factory(ast.factory))
                props: Dict[str, str] = {}
                for k, v, _ in ast.props:
                    props[k.replace("-", "_")] = v
                node = _Node(id=len(nodes), factory=ast.factory, spec=spec,
                             props=props, prop_positions=list(ast.props),
                             pos=ast.pos, name=ast.name, caps_str=ast.caps)
                nodes[node.id] = node
                self.node_of[id(ast)] = node
                if node.name is not None:
                    if node.name in self.by_name:
                        self.emit("NNS003", ERROR, ast.pos,
                                  f"duplicate element name {node.name!r}")
                    else:
                        self.by_name[node.name] = node
        return nodes

    @staticmethod
    def _suggest_factory(factory: str) -> Optional[str]:
        import difflib

        from nnstreamer_tpu.registry import ELEMENT

        close = difflib.get_close_matches(
            factory, registered_names(ELEMENT), n=1)
        return f"did you mean {close[0]!r}?" if close else None

    # -- property checks -----------------------------------------------------
    def _check_props(self, nodes: Dict[int, _Node]) -> None:
        filter_names = set(registered_names(FILTER)) | {"auto"}
        decoder_names = set(registered_names(DECODER))
        converter_names = set(registered_names(CONVERTER))
        for node in nodes.values():
            spec = node.spec
            if spec is not None:
                for k, _v, pos in node.prop_positions:
                    if k.replace("-", "_") not in spec.properties:
                        self.emit(
                            "NNS002", ERROR, pos,
                            f"{node.factory} has no property {k!r}",
                            hint=f"known properties: "
                                 f"{', '.join(sorted(spec.properties))}")
            p = node.props
            if node.factory == "tensor_filter":
                fw = p.get("framework", "auto")
                if fw not in filter_names:
                    self.emit(
                        "NNS011", ERROR, node.pos,
                        f"tensor_filter {node.label!r}: unknown framework "
                        f"{fw!r}",
                        hint=f"registered frameworks: "
                             f"{', '.join(sorted(filter_names))} (external "
                             f"subplugins load from NNSTREAMER_TPU_FILTER_"
                             f"PATH)")
            if node.factory == "tensor_decoder":
                mode = p.get("mode")
                if mode is not None and mode not in decoder_names:
                    self.emit(
                        "NNS011", ERROR, node.pos,
                        f"tensor_decoder {node.label!r}: unknown decoder "
                        f"mode {mode!r}",
                        hint=f"registered decoders: "
                             f"{', '.join(sorted(decoder_names))}")
            if node.factory == "tensor_converter":
                mode = p.get("mode")
                if mode:
                    sub = mode.split(":", 1)[1] if ":" in mode else mode
                    if sub not in converter_names:
                        self.emit(
                            "NNS011", ERROR, node.pos,
                            f"tensor_converter {node.label!r}: unknown "
                            f"converter subplugin {sub!r}",
                            hint=f"registered converters: "
                                 f"{', '.join(sorted(converter_names))}")
            if node.factory in ("tensor_mux", "tensor_merge"):
                self._check_sync(node)
            if node.factory == "queue":
                leaky = p.get("leaky", "no")
                if leaky not in ("no", "downstream"):
                    self.emit("NNS008", ERROR, node.pos,
                              f"queue {node.label!r}: unknown leaky mode "
                              f"{leaky!r} (use 'no' or 'downstream')")
                elif leaky == "downstream" and node.name is None:
                    self.emit(
                        "NNS010", WARNING, node.pos,
                        "leaky queue has no explicit name — its "
                        "nns_queue_drops_total metric gets an unstable "
                        "auto-generated label, so drops are effectively "
                        "unmonitored",
                        hint="add name=... and watch nns_queue_drops_total")

    def _check_sync(self, node: _Node) -> None:
        mode = node.props.get("sync_mode", "slowest")
        option = node.props.get("sync_option", "")
        if mode not in _SYNC_POLICIES:
            self.emit("NNS008", ERROR, node.pos,
                      f"{node.factory} {node.label!r}: unknown sync_mode "
                      f"{mode!r}",
                      hint=f"valid policies: {', '.join(_SYNC_POLICIES)}")
            return
        if mode == "basepad" and option:
            parts = str(option).split(":")
            ok = parts[0].isdigit() and (
                len(parts) == 1 or _is_number(parts[1]))
            if not ok:
                self.emit("NNS008", ERROR, node.pos,
                          f"{node.factory} {node.label!r}: basepad "
                          f"sync_option {option!r} is not "
                          f"'<pad>[:<duration>]'")
        elif mode != "basepad" and option:
            self.emit("NNS008", WARNING, node.pos,
                      f"{node.factory} {node.label!r}: sync_option "
                      f"{option!r} is ignored by sync_mode={mode}",
                      hint="only basepad consumes sync_option")

    # -- link resolution -----------------------------------------------------
    def _check_links(self, chains, nodes: Dict[int, _Node]) -> None:
        self.links: List[Tuple[int, int]] = []

        def resolve(ast) -> Optional[_Node]:
            if ast.kind in ("ref", "refpad"):
                node = self.by_name.get(ast.ref)
                if node is None:
                    self.emit("NNS004", ERROR, ast.pos,
                              f"unknown element reference {ast.ref!r}")
                return node
            return self.node_of.get(id(ast))

        def take_src(node: _Node, ast) -> bool:
            spec = node.spec
            if spec is None:
                return True
            pad = ast.pad if ast.kind == "refpad" else None
            if pad is not None and not pad.startswith("src"):
                self.emit("NNS004", ERROR, ast.pos,
                          f"{node.label!r}: {pad!r} is not a src pad")
                return False
            if spec.n_src is None:
                return True
            if node.src_used < spec.n_src or spec.requests_src:
                node.src_used += 1
                return True
            self.emit("NNS004", ERROR, ast.pos,
                      f"{node.label!r} ({node.factory}): no free src pad")
            return False

        def take_sink(node: _Node, ast) -> bool:
            spec = node.spec
            if spec is None:
                return True
            pad = ast.pad if ast.kind == "refpad" else None
            if pad is not None:
                if not pad.startswith("sink"):
                    self.emit("NNS004", ERROR, ast.pos,
                              f"{node.label!r}: {pad!r} is not a sink pad")
                    return False
                suffix = pad[len("sink_"):] if pad.startswith("sink_") \
                    else ""
                if suffix.isdigit() and spec.requests_sink:
                    # implied lower-index pads must also end up linked
                    node.sink_grown = max(node.sink_grown,
                                          int(suffix) + 1)
            if spec.n_sink is None:
                return True
            if node.sink_used < max(spec.n_sink, node.sink_grown) \
                    or spec.requests_sink:
                node.sink_used += 1
                return True
            self.emit("NNS004", ERROR, ast.pos,
                      f"{node.label!r} ({node.factory}): no free sink pad")
            return False

        for chain in chains:
            for a, b in zip(chain, chain[1:]):
                na, nb = resolve(a), resolve(b)
                if na is None or nb is None:
                    continue
                ok_src = take_src(na, a)
                ok_sink = take_sink(nb, b)
                if ok_src and ok_sink:
                    na.out_links.append(nb.id)
                    nb.in_links.append(na.id)
                    self.links.append((na.id, nb.id))

    # -- whole-graph checks --------------------------------------------------
    def _check_graph(self, nodes: Dict[int, _Node]) -> None:
        has_source = False
        for node in nodes.values():
            spec = node.spec
            if spec is None:
                continue
            if spec.is_source:
                has_source = True
            # inputs that can never receive data: a non-source element
            # with sink pads but nothing linked into it (NNS006 error:
            # runtime would silently never flow, or a sync policy would
            # wait forever)
            if (not node.in_links and not spec.is_source
                    and (spec.n_sink or 0) > 0):
                self.emit(
                    "NNS006", ERROR, node.pos,
                    f"{node.label!r} ({node.factory}): sink pad is never "
                    f"linked — no data will ever reach it")
            # implied request-sink pads (mux m.sink_2 referenced, but
            # fewer links made) — the same condition parse_launch rejects
            if node.sink_grown > len(node.in_links):
                self.emit(
                    "NNS006", ERROR, node.pos,
                    f"{node.label!r} ({node.factory}): sink pads up to "
                    f"index {node.sink_grown - 1} are implied but only "
                    f"{len(node.in_links)} link(s) were made — a sync "
                    f"policy would wait on the missing inputs forever")
            # outputs nobody consumes (runtime drops them; usually a
            # missing sink or a forgotten branch)
            if (spec.n_src or 0) > 0 and not node.out_links \
                    and not spec.is_sink:
                self.emit(
                    "NNS006", WARNING, node.pos,
                    f"{node.label!r} ({node.factory}): src pad is "
                    f"unlinked — its output is dropped",
                    hint="terminate the chain with a sink element")
            if node.factory == "tee" and len(node.out_links) >= 2:
                for dst in node.out_links:
                    if nodes[dst].factory != "queue":
                        self.emit(
                            "NNS009", WARNING, node.pos,
                            f"tee {node.label!r}: branch into "
                            f"{nodes[dst].label!r} has no queue — all "
                            f"branches run serially on one thread, and a "
                            f"blocking branch starves the others",
                            hint="start each tee branch with queue")
        if nodes and not has_source:
            self.emit("NNS006", WARNING,
                      min(n.pos for n in nodes.values()),
                      "pipeline has no source element — nothing will "
                      "ever flow")
        self._check_cycles(nodes)

    def _check_cycles(self, nodes: Dict[int, _Node]) -> None:
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {i: WHITE for i in nodes}
        self.has_cycle = False

        def dfs(u: int, path: List[int]) -> None:
            color[u] = GRAY
            path.append(u)
            for v in nodes[u].out_links:
                if color[v] == GRAY:
                    cyc = path[path.index(v):] + [v]
                    names = " -> ".join(nodes[i].label for i in cyc)
                    self.emit("NNS007", ERROR, nodes[v].pos,
                              f"cycle in pipeline graph: {names}",
                              hint="recurrence belongs in tensor_reposrc/"
                                   "tensor_reposink slots, not pad links")
                    self.has_cycle = True
                elif color[v] == WHITE:
                    dfs(v, path)
            path.pop()
            color[u] = BLACK

        for i in nodes:
            if color[i] == WHITE:
                dfs(i, [])

    # -- caps/dtype/shape propagation ----------------------------------------
    def _propagate_caps(self, nodes: Dict[int, _Node]) -> None:
        if getattr(self, "has_cycle", False):
            return  # no topological order to walk
        order = self._topo(nodes)
        out_caps: Dict[int, Optional[Caps]] = {}
        for nid in order:
            node = nodes[nid]
            in_caps = None
            for src in node.in_links:
                c = out_caps.get(src)
                if c is not None:
                    in_caps = c
                    self._check_media(nodes[src], node, c)
            out_caps[nid] = self._derive_out(node, in_caps)

    def _topo(self, nodes: Dict[int, _Node]) -> List[int]:
        indeg = {i: len(n.in_links) for i, n in nodes.items()}
        ready = [i for i, d in indeg.items() if d == 0]
        order: List[int] = []
        while ready:
            u = ready.pop()
            order.append(u)
            for v in nodes[u].out_links:
                indeg[v] -= 1
                if indeg[v] == 0:
                    ready.append(v)
        return order

    def _check_media(self, src: _Node, dst: _Node, caps: Caps) -> None:
        spec = dst.spec
        if spec is None or spec.media_in is None:
            return
        if caps.name not in spec.media_in:
            hint = None
            if caps.name in ("video/x-raw", "audio/x-raw",
                            "application/octet-stream") and \
                    "other/tensors" in spec.media_in:
                hint = (f"insert tensor_converter between "
                        f"{src.label!r} and {dst.label!r}")
            self.emit(
                "NNS005", ERROR, dst.pos,
                f"link {src.label!r} -> {dst.label!r}: caps "
                f"{caps.name!r} do not intersect with accepted types "
                f"{{{', '.join(sorted(spec.media_in))}}}", hint=hint)

    def _derive_out(self, node: _Node,
                    in_caps: Optional[Caps]) -> Optional[Caps]:
        f = node.factory
        spec = node.spec
        if spec is None:
            return None
        if spec.is_source:
            return static_src_caps(spec, node.props)
        if f in PASSTHROUGH:
            return in_caps
        if f == "capsfilter":
            want = self._capsfilter_caps(node)
            if want is None:
                return in_caps
            if in_caps is None:
                return want
            merged = in_caps.intersect(want)
            if merged is None:
                self.emit(
                    "NNS005", ERROR, node.pos,
                    f"capsfilter {node.label!r}: upstream caps "
                    f"{in_caps!r} do not intersect filter {want!r}")
                return None
            return merged
        if f == "tensor_converter" and in_caps is not None:
            return self._converter_out(node, in_caps)
        return None  # format settles at runtime; propagation stops here

    def _capsfilter_caps(self, node: _Node) -> Optional[Caps]:
        from nnstreamer_tpu.pipeline.parse import parse_caps_string

        raw = node.caps_str or node.props.get("caps")
        if not raw:
            return None
        try:
            return parse_caps_string(raw)
        except ValueError as e:
            self.emit("NNS012", ERROR, node.pos,
                      f"capsfilter {node.label!r}: bad caps string: {e}")
            return None

    def _converter_out(self, node: _Node,
                       in_caps: Caps) -> Optional[Caps]:
        """Derive converter output caps by asking the REAL negotiation
        code (``TensorConverter._derive_config``) — a throwaway instance
        holds no runtime state, and reusing it means the verifier can
        never drift from what negotiation will actually do."""
        try:
            inst = node.spec.klass()
            for k, v in node.props.items():
                if k != "name":
                    inst.set_property(k, v)
            cfg = inst._derive_config(in_caps)
        except Exception as e:  # noqa: BLE001 — any failure here IS the
            # negotiation failure runtime would hit on the first buffer
            self.emit(
                "NNS005", ERROR, node.pos,
                f"tensor_converter {node.label!r} cannot negotiate "
                f"upstream caps {in_caps!r}: {e}")
            return None
        return cfg.to_caps() if cfg is not None else None


def _is_number(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


def verify_description(description: str,
                       source: str = "<description>") -> List[Diagnostic]:
    """Statically verify an nns-launch description. Returns diagnostics
    (possibly empty); never raises on a malformed description — syntax
    errors come back as NNS012."""
    return _Verifier(description, source).run()


def verify_pipeline(pipe) -> List[Diagnostic]:
    """Pre-flight an already-constructed :class:`Pipeline` (programmatic
    builders): dangling pads, cycles, sync-policy conflicts, tee fan-out
    without queues. Exposed as ``Pipeline.verify()``."""
    from nnstreamer_tpu.pipeline.pipeline import Queue, SourceElement

    diags: List[Diagnostic] = []
    src = f"<pipeline:{pipe.name}>"

    def emit(code, severity, message, hint=None):
        diags.append(Diagnostic(code, severity, Location(src), message,
                                hint))

    has_source = False
    for el in pipe.elements:
        if isinstance(el, SourceElement):
            has_source = True
        for p in el.sinkpads:
            if p.peer is None:
                emit("NNS006", ERROR,
                     f"{el.name!r} ({el.ELEMENT_NAME}): sink pad "
                     f"{p.name!r} is never linked — no data will ever "
                     f"reach it")
        if not isinstance(el, SourceElement) or el.srcpads:
            unlinked = [p.name for p in el.srcpads if p.peer is None]
            if unlinked and len(unlinked) == len(el.srcpads) \
                    and el.srcpads:
                emit("NNS006", WARNING,
                     f"{el.name!r} ({el.ELEMENT_NAME}): src pad(s) "
                     f"{unlinked} unlinked — output is dropped")
        if el.ELEMENT_NAME in ("tensor_mux", "tensor_merge"):
            mode = el.get_property("sync_mode")
            if mode not in _SYNC_POLICIES:
                emit("NNS008", ERROR,
                     f"{el.name!r}: unknown sync_mode {mode!r}",
                     hint=f"valid policies: {', '.join(_SYNC_POLICIES)}")
        if el.ELEMENT_NAME == "tee" and len(el.srcpads) >= 2:
            for p in el.srcpads:
                peer = p.peer.element if p.peer is not None else None
                if peer is not None and not isinstance(peer, Queue):
                    emit("NNS009", WARNING,
                         f"tee {el.name!r}: branch into {peer.name!r} "
                         f"has no queue — branches run serially",
                         hint="start each tee branch with a queue")
    if pipe.elements and not has_source:
        emit("NNS006", WARNING,
             "pipeline has no source element — nothing will ever flow")

    # cycle check over pad links
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {id(el): WHITE for el in pipe.elements}

    def dfs(el, path):
        color[id(el)] = GRAY
        path.append(el)
        for p in el.srcpads:
            if p.peer is None:
                continue
            nxt = p.peer.element
            if color.get(id(nxt)) == GRAY:
                names = " -> ".join(e.name for e in path) + f" -> {nxt.name}"
                emit("NNS007", ERROR,
                     f"cycle in pipeline graph: {names}")
            elif color.get(id(nxt)) == WHITE:
                dfs(nxt, path)
        path.pop()
        color[id(el)] = BLACK

    for el in pipe.elements:
        if color[id(el)] == WHITE:
            dfs(el, [])
    return sort_diagnostics(diags)
