"""Project-invariant AST lint — the ``NNS1xx`` half of ``nns-lint``.

These rules encode invariants this codebase has already been burned by
(see docs/linting.md for the rationale of each):

- NNS101: ``time.time()`` measures wall-clock, which jumps under NTP
  steps; durations and deadlines must use ``time.monotonic()``. Binding
  the value to a ``wall*``-prefixed name marks the intentional wall-clock
  uses (export timestamps) without a pragma.
- NNS102: sleeping, joining a thread, or doing socket IO while holding a
  lock serializes every other waiter behind the blocking call.
- NNS103: library code logs through ``utils/log.py``; ``print`` is only
  for CLI entry points.
- NNS104: a bare ``except:`` (or ``except Exception: pass``) swallows
  ``KeyboardInterrupt``/bugs silently.
- NNS105: a ``threading.Thread`` without an explicit ``daemon=`` choice
  inherits it implicitly — shutdown behavior should be a decision, not an
  accident.
- NNS106: metric names must follow ``nns_<subsystem>_...`` so dashboards
  can group by prefix.
- NNS107: sync-forcing calls (``np.asarray``, ``.block_until_ready()``,
  ``float(x[...])``) inside per-frame hot paths (``chain`` /
  ``chain_list`` / ``_chain_locked`` / ``device_stage``) silently
  collapse the dispatch window (``pipeline/dispatch.py``) back to
  synchronous dispatch — materialize at the fence or sink instead.
- NNS108: materializing a buffer's tensors directly
  (``np.asarray(buf.tensors[i])``, ``jax.device_get(...)``,
  ``.addressable_data(...)``) bypasses the residency layer's one
  sanctioned ``to_host()`` site (``tensors/buffer.py``): a
  ``DeviceBuffer`` caches its host view there, so a direct fetch copies
  the same bytes again AND dodges the transfer counters the bench and
  the ``nns_buffer_resident_ratio`` gauge rely on.
- NNS109: a class that declares ``REORDER_SAFE = True`` while its
  per-frame ``chain``/``chain_list`` mutates ``self`` state: the ingest
  lane planner (``pipeline/lanes.py``) replicates such elements across
  parallel worker lanes and processes frames out of order — per-frame
  mutable attributes make each lane's clone diverge from the serial
  element, so the "byte-identical to lanes=1" contract silently breaks.
- NNS110: a blocking sleep or unbounded wait (``.wait()``/``.get()``/
  ``.acquire()``/``.join()`` with no timeout) inside a scheduler or
  dispatch hot path (admission, EDF drain, feedback-controller step —
  see ``_SCHED_HOT_FUNCS``): the SLO scheduler's whole deadline math
  assumes these paths are event-driven and O(work); one
  ``time.sleep``-style pacing loop or forever-wait turns every
  admission decision stale and stalls EOS/teardown behind it.
- NNS111: a broad ``except Exception``/``BaseException`` inside an
  element chain or worker loop (``chain`` / ``chain_list`` /
  ``run_loop`` / ``_worker`` / ``_drain`` / ``_drain_sched`` /
  ``_drain_loop`` — see ``_WORKER_FUNCS``) whose handler neither
  re-raises nor posts to the pipeline bus
  (``post_error``/``post_message``/``post_warning``): these are the
  exception boundaries the supervision layer (``pipeline/supervise.py``)
  and the bus ``wait()`` contract rely on — a handler that only logs
  (or does nothing) converts a dead frame into a silent hang, because
  downstream never sees an error message and EOS never arrives.
- NNS112: socket/channel IO without an explicit timeout inside a
  transport hot path (connect, framed send/recv, result routing,
  broker publish — see ``_TRANSPORT_HOT_FUNCS``): the resilience layer
  (``query/resilience.py``) can only retry, hedge, or trip a breaker
  when the underlying call BOUNDS its wait — an untimed ``connect()``
  or ``recv()`` turns a dead peer into an indefinite hang that no
  deadline or supervisor ever sees. A call is fine when the enclosing
  function passes ``timeout=`` at the call, calls ``settimeout(...)``
  on the socket, or sets ``SO_SNDTIMEO``/``SO_RCVTIMEO`` (the
  send-side discipline used by ``query/mqtt.py``).
- NNS113: a direct ``jax.device_put`` outside the HBM budget
  accountant's tracked entry points (``TensorBuffer.to_device`` /
  ``upload_many``, the backend ``open()`` weight load and
  ``install_weights()`` swap — see ``_MEM_SANCTIONED_FUNCS``): bytes
  it moves land in device memory
  that ``nns_mem_used_bytes`` never sees, so the pressure ladder and
  residency eviction math (``tensors/memory.py``) run against an
  undercount exactly when HBM is the scarce resource.
- NNS114: an unbounded container fed from an obs hot-path recording
  function (``span``/``mark``/``observe``/``record*``/``note*``/
  ``add`` — see ``_OBS_RECORD_FUNCS``) in the ``obs`` package: a
  ``deque()`` built without ``maxlen``, or ``self.x.append(...)``
  where ``__init__`` bound ``self.x`` to a bare ``[]``/``list()``/
  unbounded ``deque()``. The always-on telemetry layer (flight
  recorder, timeline rings, quantile estimators) records on EVERY
  frame for the life of the process — one unbounded append there is a
  slow memory leak in the exact component that must never cost
  anything. Bounded-by-construction exceptions take a pragma.
- NNS115: a checkpointable class whose save/load key sets drift. For a
  class defining a ``snapshot()``/``restore()`` or
  ``checkpoint_state()``/``restore_state()`` pair (the serving-
  continuity protocol, ``pipeline/continuity.py``), the string-literal
  keys the save method writes must equal the keys the load method
  reads: a key saved but never restored is dead state that silently
  stops round-tripping, a key restored but never saved reads as absent
  on every real checkpoint. Classes whose schema is dynamic (no
  literal keys on one side, e.g. ``TensorRepo``) are skipped.
- NNS117: a GSPMD sharding constructed outside the ``parallel``
  package: ``NamedSharding``/``PositionalSharding`` instantiation, a
  ``shard_map`` wrap, or a ``pjit`` call anywhere else scatters
  device-placement decisions across the codebase. The serving plane
  (``parallel/serve.py``) and the scaling toolbox (``parallel/
  {mesh,sharded,ring,pipeline}.py``) are the audited homes for every
  sharding: that is what makes the matched-sharding hand-off contract
  and the per-shard HBM accounting enforceable. Callers pass a
  mesh-spec string (``mesh=dp4``) or a plan object around instead.
- NNS116: a wire-header ``struct.Struct`` whose field count disagrees
  with a pack/unpack site. For every ``NAME = struct.Struct("<fmt>")``
  binding in a file, each ``NAME.pack(...)`` must pass exactly as many
  values as the format has fields, and each tuple-unpacking
  ``a, b, ... = NAME.unpack[_from](...)`` must bind exactly that many
  names. The query protocol's framed headers (``_HDR``, ``_EXT_HDR``,
  ``_EXT2_HDR``, ...) are evolved by editing the format string and its
  pack/unpack sites in separate places — a count mismatch raises only
  at runtime, on the first real frame, usually on the peer.
- NNS118: a direct subscript of a paged KV arena (a name whose final
  component is ``arena``/``_arena``/``*_arena``, ``.at[...]`` included)
  outside ``serving/kvpool.py``: the block pool is the one audited home
  for host-side arena reads and mutations — refcounts, buffer donation,
  and the zero-block/sentinel invariants all live there, and a raw
  ``arena[...]`` elsewhere silently breaks them (a freed block's bytes
  read as stale history, a donated buffer is use-after-free). The
  model-side paged builders never see the arena whole; they receive
  per-layer slices from the decode scan.
- NNS119: a hard-coded ``host:port`` string literal outside
  ``query/discovery.py``, config modules, and tests. A replicated fleet
  (serving/fleet.py) moves endpoints at every deploy — replicas bind
  ephemeral ports and re-advertise through the broker — so a baked-in
  endpoint silently pins code to one replica and bypasses discovery,
  the breaker, and the balancer. Endpoints belong in element properties
  (``servers=``/``operation=``), CLI flags, or discovery ads; the
  discovery module itself and configuration defaults are the audited
  homes for literal endpoints.

Findings are suppressed per-line with::

    # nns-lint: disable=NNS101 -- <why this line is an exception>

A pragma with no justification is itself a finding (NNS199).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from nnstreamer_tpu.analysis.diagnostics import (
    ERROR,
    Diagnostic,
    Location,
    sort_diagnostics,
)

_PRAGMA_RE = re.compile(
    r"#\s*nns-lint:\s*disable=([A-Z0-9,]+)(?:\s+--\s*(\S.*))?")

#: metric-registry constructor methods whose first argument is the name
_METRIC_METHODS = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = re.compile(r"^nns_[a-z0-9]+(_[a-z0-9]+)+$")

#: socket methods that block on the network
_SOCKET_BLOCKING = {"recv", "recvfrom", "recv_into", "accept", "connect",
                    "sendall", "sendto"}

#: NNS119: a full-string ``host:port`` endpoint literal. The host part
#: must contain a letter or a dot so times ("12:30") and ratios never
#: match; the port is 2-5 digits so drive letters ("C:1") stay out
_HOSTPORT_RE = re.compile(
    r"^[A-Za-z0-9_.\-]*[A-Za-z.][A-Za-z0-9_.\-]*:\d{2,5}$")

#: sync-forcing callables by dotted name (NNS107): each one blocks the
#: caller until outstanding device work retires (or copies D2H, which
#: implies the same)
_SYNC_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.block_until_ready"}
#: per-frame hot-path function names where a hidden sync defeats the
#: inflight dispatch window (pipeline/dispatch.py)
_HOT_FUNCS = {"chain", "chain_list", "_chain_locked", "device_stage"}

#: scheduler/dispatch hot-path function names (NNS110): the admission,
#: EDF-drain and feedback-control paths the SLO scheduler's deadline
#: math assumes are event-driven — a sleep or forever-wait here makes
#: every admission decision stale and wedges EOS behind it
_SCHED_HOT_FUNCS = {"admit", "admit_request", "decide", "note_shed",
                    "observe_service", "observe_completion", "maybe_step",
                    "record_completion", "_apply_knobs",
                    "_chain_scheduled", "_shed_one_locked", "_flush_edf",
                    "_drain_sched", "_drain", "dispatch", "fence"}
#: attribute calls that block forever unless given a timeout
_UNBOUNDED_WAIT_ATTRS = {"wait", "wait_for", "acquire", "join", "get"}

#: element-chain / worker-loop function names (NNS111): the exception
#: boundaries that must either re-raise (so _chain_entry's policy
#: dispatch sees the failure) or post to the pipeline bus (so wait()
#: unblocks) — swallowing here turns one dead frame into a silent hang
_WORKER_FUNCS = {"chain", "chain_list", "run_loop", "_worker",
                 "_drain", "_drain_sched", "_drain_loop"}
#: bus-posting method names that count as surfacing the failure
_BUS_POST_ATTRS = {"post_error", "post_message", "post_warning"}

#: transport hot-path function names (NNS112): connection setup, framed
#: send/recv, result routing and broker publish — the paths where an
#: untimed socket wait hangs forever instead of feeding the resilience
#: layer's retry/hedge/breaker machinery
_TRANSPORT_HOT_FUNCS = {"connect", "_connect_one", "send_msg", "recv_msg",
                        "_send_buf", "_recv_result", "_r_recv", "_r_hello",
                        "send_result", "send_expired", "send_stream",
                        "recv_stream", "publish", "_recover"}

#: direct-materialization callables (NNS108): fetch device bytes while
#: bypassing the cached, counted to_host() path
_MATERIALIZE_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get"}
#: functions that ARE the sanctioned materialization site — anything
#: inside them is exempt from NNS108
_SANCTIONED_FUNCS = {"to_host"}

#: the HBM budget accountant's tracked entry points (NNS113): the only
#: functions allowed to call jax.device_put directly, because they are
#: where the moved bytes register against tensors/memory.py — to_device/
#: upload_many (frame transfers), the backend open() weight load and
#: install_weights() swap (residency-unit registration)
_MEM_SANCTIONED_FUNCS = {"to_device", "upload_many", "open",
                         "install_weights", "_register_resident"}

#: sharding-construction callables (NNS117): allowed only inside the
#: ``parallel`` package — the audited home of every placement decision
_SHARDING_CTORS = {"NamedSharding", "jax.sharding.NamedSharding",
                   "sharding.NamedSharding",
                   "PositionalSharding", "jax.sharding.PositionalSharding",
                   "shard_map", "jax.shard_map",
                   "shard_map.shard_map",
                   "jax.experimental.shard_map.shard_map",
                   "pjit", "jax.experimental.pjit.pjit", "pjit.pjit"}

#: obs hot-path recording function names (NNS114): the per-frame /
#: per-event entry points of the always-on telemetry layer — anything
#: they grow must be bounded
_OBS_RECORD_FUNCS = {"span", "mark", "observe", "add", "inc",
                     "async_begin", "async_end"}
#: recording-function name prefixes (record_completion, note_retry,
#: observe_invoke, _observe_locked, _complete, ...)
_OBS_RECORD_PREFIXES = ("record", "_record", "note", "_note",
                        "observe", "_observe", "_complete")


#: checkpoint save/load method-name pairs (NNS115): the serving-
#: continuity protocol's state round-trip — reporting-only snapshots
#: (no matching load method) are not checked
_CKPT_PAIRS = (("snapshot", "restore"),
               ("checkpoint_state", "restore_state"))


def _is_obs_record_func(name: str) -> bool:
    return name in _OBS_RECORD_FUNCS or \
        name.startswith(_OBS_RECORD_PREFIXES)


def _struct_field_count(fmt: str) -> Optional[int]:
    """Exact field count of a struct format string, or None when the
    format itself is invalid (that's the runtime's error to raise, not
    a lint finding). Computed by the struct module itself — pad bytes,
    repeat counts, and the s/p single-field rules come out right by
    construction."""
    import struct as _struct

    try:
        st = _struct.Struct(fmt)
        return len(st.unpack(bytes(st.size)))
    except _struct.error:
        return None


def _parse_pragmas(text: str) -> Tuple[Dict[int, Set[str]], List[int]]:
    """Per-line suppressed codes, plus lines with a reasonless pragma."""
    suppressed: Dict[int, Set[str]] = {}
    missing_reason: List[int] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        suppressed[lineno] = codes
        if not m.group(2):
            missing_reason.append(lineno)
    return suppressed, missing_reason


def _dotted(node: ast.AST) -> str:
    """'time.time' for Attribute/Name chains, '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: Path, tree: ast.Module, text: str,
                 rel: str):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.text = text
        self.diags: List[Diagnostic] = []
        self._lock_depth = 0
        self._func_stack: List[str] = []
        #: the actual FunctionDef nodes of the stack (NNS112 walks the
        #: enclosing function body for timeout discipline)
        self._func_nodes: List[ast.AST] = []
        self._timeout_discipline: Dict[int, bool] = {}  # id(fnode) → bool
        self._wall_lines: Set[int] = set()
        self._collect_wall_bindings(tree)
        #: NNS116: NAME → field count for every ``NAME = struct.Struct(
        #: "<literal>")`` binding in this file
        self._struct_fields: Dict[str, int] = {}
        self._collect_struct_bindings(tree)
        #: NNS114 applies only inside the obs package
        self._in_obs = "obs" in Path(rel).parts
        #: NNS117 exempts the parallel package — the one audited home
        #: where shardings may be constructed
        self._in_parallel = "parallel" in Path(rel).parts
        #: NNS118 exempts the block pool itself — the one audited home
        #: for direct KV-arena indexing
        self._in_kvpool = Path(rel).name == "kvpool.py"
        #: NNS119 exempts the discovery module (the audited home for
        #: endpoint strings), config modules, and test code
        parts = Path(rel).parts
        fname = Path(rel).name
        self._nns119_exempt = (
            fname == "discovery.py"
            or fname in ("config.py", "settings.py", "conftest.py")
            or "tests" in parts
            or fname.startswith("test_"))

    # -- helpers -------------------------------------------------------------
    def emit(self, code: str, node: ast.AST, message: str,
             hint: Optional[str] = None) -> None:
        loc = Location(self.rel, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1)
        self.diags.append(Diagnostic(code, ERROR, loc, message, hint))

    def _collect_wall_bindings(self, tree: ast.Module) -> None:
        """Lines where time.time() is bound to a wall*-prefixed name —
        the in-code way to mark deliberate wall-clock reads."""
        for node in ast.walk(tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                name = t.attr if isinstance(t, ast.Attribute) else \
                    t.id if isinstance(t, ast.Name) else ""
                if name.startswith("wall"):
                    for sub in ast.walk(node):
                        if hasattr(sub, "lineno"):
                            self._wall_lines.add(sub.lineno)

    def _collect_struct_bindings(self, tree: ast.Module) -> None:
        """``NAME = struct.Struct("<literal fmt>")`` bindings anywhere in
        the file (module or class level) — the wire headers NNS116
        checks pack/unpack sites against. A name bound twice with
        different formats is ambiguous and dropped."""
        ambiguous: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and _dotted(value.func) in ("struct.Struct", "Struct")
                    and value.args
                    and isinstance(value.args[0], ast.Constant)
                    and isinstance(value.args[0].value, str)):
                continue
            count = _struct_field_count(value.args[0].value)
            if count is None:
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                prior = self._struct_fields.get(t.id)
                if prior is not None and prior != count:
                    ambiguous.add(t.id)
                self._struct_fields[t.id] = count
        for name in ambiguous:
            self._struct_fields.pop(name, None)

    # -- visitors ------------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        is_lock = any("lock" in _dotted(item.context_expr.func
                                        if isinstance(item.context_expr,
                                                      ast.Call)
                                        else item.context_expr).lower()
                      for item in node.items)
        if is_lock:
            self._lock_depth += 1
            self.generic_visit(node)
            self._lock_depth -= 1
        else:
            self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func_stack.append(node.name)
        self._func_nodes.append(node)
        self.generic_visit(node)
        self._func_nodes.pop()
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        self._rule_nns101(node, dotted)
        if self._lock_depth:
            self._rule_nns102(node, dotted)
        self._rule_nns103(node, dotted)
        self._rule_nns105(node, dotted)
        self._rule_nns106(node, dotted)
        self._rule_nns107(node, dotted)
        self._rule_nns108(node, dotted)
        self._rule_nns110(node, dotted)
        self._rule_nns112(node, dotted)
        self._rule_nns113(node, dotted)
        self._rule_nns114_deque(node, dotted)
        self._rule_nns117(node, dotted)
        self._rule_nns116_pack(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._rule_nns116_unpack(node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        self._rule_nns118(node)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        self._rule_nns119(node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._rule_nns104(node)
        self._rule_nns111(node)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._rule_nns109(node)
        self._rule_nns114_append(node)
        self._rule_nns115(node)
        self.generic_visit(node)

    # -- rules ---------------------------------------------------------------
    def _rule_nns101(self, node: ast.Call, dotted: str) -> None:
        if dotted != "time.time":
            return
        if node.lineno in self._wall_lines:
            return
        self.emit(
            "NNS101", node,
            "time.time() is wall-clock and jumps under NTP steps — use "
            "time.monotonic() for durations and deadlines",
            hint="if this really is an export timestamp, bind it to a "
                 "wall*-prefixed name or add a justified pragma")

    def _rule_nns102(self, node: ast.Call, dotted: str) -> None:
        blocking: Optional[str] = None
        if dotted == "time.sleep":
            blocking = "time.sleep"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "join" and self._looks_like_thread_join(node):
                blocking = "thread join"
            elif attr in _SOCKET_BLOCKING:
                blocking = f"socket .{attr}()"
        if blocking:
            self.emit(
                "NNS102", node,
                f"{blocking} while holding a lock — every other waiter "
                f"stalls behind this call",
                hint="copy state under the lock, block outside it")

    @staticmethod
    def _looks_like_thread_join(node: ast.Call) -> bool:
        """Disambiguate Thread.join from str.join: a thread join takes
        no args, a timeout kwarg, or a single numeric positional."""
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
        if not node.args and not node.keywords:
            return True
        if len(node.args) == 1 and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, (int, float)) \
                and not isinstance(node.args[0].value, bool):
            return True
        return False

    def _rule_nns103(self, node: ast.Call, dotted: str) -> None:
        if dotted != "print":
            return
        if self.path.name == "cli.py" or "main" in self._func_stack:
            return
        self.emit(
            "NNS103", node,
            "print() in library code bypasses the logging pipeline",
            hint="use nnstreamer_tpu.utils.log (or move this into a CLI "
                 "main())")

    def _rule_nns104(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.emit(
                "NNS104", node,
                "bare 'except:' also catches KeyboardInterrupt/SystemExit",
                hint="name the exception type (Exception at the broadest)")
            return
        names = [_dotted(node.type)]
        if isinstance(node.type, ast.Tuple):
            names = [_dotted(e) for e in node.type.elts]
        broad = any(n in ("Exception", "BaseException") for n in names)
        body_is_pass = all(isinstance(s, ast.Pass) for s in node.body)
        if broad and body_is_pass:
            self.emit(
                "NNS104", node,
                "'except Exception: pass' silently swallows every bug",
                hint="log the exception, narrow the type, or justify "
                     "with a pragma")

    def _rule_nns111(self, node: ast.ExceptHandler) -> None:
        if not any(f in _WORKER_FUNCS for f in self._func_stack):
            return
        if node.type is None:
            return  # bare except: is NNS104's finding already
        names = [_dotted(node.type)]
        if isinstance(node.type, ast.Tuple):
            names = [_dotted(e) for e in node.type.elts]
        if not any(n in ("Exception", "BaseException") for n in names):
            return
        if all(isinstance(s, ast.Pass) for s in node.body):
            return  # broad+pass is NNS104's finding already
        for sub in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if isinstance(sub, ast.Raise):
                return
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _BUS_POST_ATTRS:
                return
        self.emit(
            "NNS111", node,
            "broad except in an element chain/worker loop that neither "
            "re-raises nor posts to the pipeline bus — the dead frame "
            "becomes a silent hang (no error message, no EOS)",
            hint="re-raise (let _chain_entry's error-policy handle it), "
                 "call post_error/post_warning, or justify with a pragma")

    def _rule_nns105(self, node: ast.Call, dotted: str) -> None:
        if dotted not in ("threading.Thread", "Thread"):
            return
        if any(kw.arg == "daemon" for kw in node.keywords):
            return
        self.emit(
            "NNS105", node,
            "Thread without an explicit daemon= choice — shutdown "
            "behavior becomes an accident of the spawning thread",
            hint="pass daemon=True (reaped at exit) or daemon=False "
                 "(must be joined), whichever you actually mean")

    def _rule_nns106(self, node: ast.Call, dotted: str) -> None:
        if not isinstance(node.func, ast.Attribute) or \
                node.func.attr not in _METRIC_METHODS:
            return
        if not node.args:
            return
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            return
        name = first.value
        if not _METRIC_NAME_RE.match(name):
            self.emit(
                "NNS106", first,
                f"metric name {name!r} violates the nns_<subsystem>_... "
                f"convention",
                hint="lowercase, nns_ prefix, >=2 more _-separated parts")

    def _rule_nns107(self, node: ast.Call, dotted: str) -> None:
        if not any(f in _HOT_FUNCS for f in self._func_stack):
            return
        what: Optional[str] = None
        if dotted in _SYNC_CALLS:
            what = f"{dotted}()"
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            what = ".block_until_ready()"
        elif dotted in ("float", "int") and len(node.args) == 1 and \
                isinstance(node.args[0], ast.Subscript):
            # float(out[0]) / int(scores[i]) on a device array blocks on
            # the whole dispatch to fetch one scalar
            what = f"{dotted}(x[...])"
        if what is None:
            return
        self.emit(
            "NNS107", node,
            f"{what} in a per-frame hot path forces a device sync — the "
            f"inflight dispatch window silently collapses to synchronous "
            f"dispatch",
            hint="materialize at the fence/sink (to_host, "
                 "materialize-host queue) or justify host-only use with "
                 "a pragma")

    def _rule_nns108(self, node: ast.Call, dotted: str) -> None:
        if any(f in _SANCTIONED_FUNCS for f in self._func_stack):
            return
        what: Optional[str] = None
        if dotted in _MATERIALIZE_CALLS and node.args and \
                self._touches_buffer_tensors(node.args[0]):
            # np.asarray(buf.tensors[i]) — fetching a buffer's payload
            # around the wrapper; plain np.asarray(x) on a loose array
            # is NNS107's business, not this rule's
            what = f"{dotted}(...tensors...)"
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "addressable_data":
            what = ".addressable_data(...)"
        if what is None:
            return
        self.emit(
            "NNS108", node,
            f"{what} materializes buffer tensors around the sanctioned "
            f"to_host() site — a DeviceBuffer's cached host view is "
            f"bypassed (double copy) and the nns_transfer_* counters "
            f"miss the fetch",
            hint="call buf.to_host() (cached, counted) or justify a "
                 "host-only payload with a pragma")

    def _rule_nns110(self, node: ast.Call, dotted: str) -> None:
        if not any(f in _SCHED_HOT_FUNCS for f in self._func_stack):
            return
        what: Optional[str] = None
        if dotted == "time.sleep":
            what = "time.sleep()"
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _UNBOUNDED_WAIT_ATTRS and \
                not self._is_bounded_wait(node):
            what = f".{node.func.attr}() without a timeout"
        if what is None:
            return
        self.emit(
            "NNS110", node,
            f"{what} in a scheduler/dispatch hot path — deadline "
            f"admission assumes these paths are event-driven; a sleep or "
            f"forever-wait makes every admission decision stale and "
            f"stalls EOS/teardown behind it",
            hint="bound the wait (timeout=...), restructure around a "
                 "wake token/condition with a deadline, or justify with "
                 "a pragma")

    @staticmethod
    def _is_bounded_wait(node: ast.Call) -> bool:
        """A wait call is bounded when it passes any timeout: a
        ``timeout=`` kwarg, or a positional argument (``ev.wait(0.5)``,
        ``cv.wait_for(pred, 0.5)`` — and ``d.get(key[, default])`` /
        ``sem.acquire(False)`` stop being forever-blocking calls at
        all, so any-positional is the conservative no-finding side)."""
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
        if node.func.attr == "wait_for":
            return len(node.args) > 1
        return bool(node.args)

    def _rule_nns112(self, node: ast.Call, dotted: str) -> None:
        if not any(f in _TRANSPORT_HOT_FUNCS for f in self._func_stack):
            return
        what: Optional[str] = None
        if dotted.endswith("create_connection") and \
                not any(kw.arg == "timeout" for kw in node.keywords) and \
                len(node.args) < 2:
            # create_connection(addr[, timeout]) — positional 2nd arg IS
            # the timeout, so only the one-arg untimed form is a finding
            what = "create_connection() without a timeout"
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SOCKET_BLOCKING and \
                not any(kw.arg == "timeout" for kw in node.keywords) and \
                not self._enclosing_has_timeout_discipline():
            what = f"socket .{node.func.attr}() with no timeout " \
                   f"discipline in scope"
        if what is None:
            return
        self.emit(
            "NNS112", node,
            f"{what} in a transport hot path — a dead peer becomes an "
            f"indefinite hang the retry/hedge/breaker machinery never "
            f"observes",
            hint="pass timeout=, call settimeout(...) in this function, "
                 "set SO_SNDTIMEO/SO_RCVTIMEO, or justify with a pragma")

    def _rule_nns113(self, node: ast.Call, dotted: str) -> None:
        if dotted != "jax.device_put":
            return
        if any(f in _MEM_SANCTIONED_FUNCS for f in self._func_stack):
            return
        self.emit(
            "NNS113", node,
            "direct jax.device_put outside the HBM budget accountant's "
            "tracked entry points — the moved bytes never register "
            "against nns_mem_used_bytes, so the pressure ladder and "
            "residency eviction math run on an undercount",
            hint="route the upload through TensorBuffer.to_device/"
                 "upload_many, register the bytes with tensors/memory.py "
                 "(residency unit or note_h2d), or justify with a pragma")

    def _rule_nns117(self, node: ast.Call, dotted: str) -> None:
        if self._in_parallel or dotted not in _SHARDING_CTORS:
            return
        self.emit(
            "NNS117", node,
            f"{dotted}(...) constructs a GSPMD sharding outside the "
            f"parallel package — placement decisions scattered across "
            f"the codebase break the matched-sharding hand-off contract "
            f"and the per-shard HBM accounting that parallel/serve.py "
            f"makes auditable",
            hint="name a mesh spec (mesh=dp4 / get_mesh_plan) and use "
                 "the plan's batched()/replicated() shardings, or add a "
                 "helper in parallel/ — or justify with a pragma")

    def _rule_nns118(self, node: ast.Subscript) -> None:
        if self._in_kvpool:
            return
        dotted = _dotted(node.value)
        if dotted.endswith(".at"):
            dotted = dotted[:-len(".at")]  # x.arena.at[...] indexes x.arena
        if not dotted:
            return
        last = dotted.rsplit(".", 1)[-1]
        if not (last in ("arena", "_arena") or last.endswith("_arena")):
            return
        self.emit(
            "NNS118", node,
            f"direct subscript of KV arena {dotted!r} outside "
            f"serving/kvpool.py — block refcounts, buffer donation, and "
            f"the zero-block/sentinel invariants live in the pool; a raw "
            f"arena index elsewhere can read a freed block's stale bytes "
            f"or write through a donated buffer",
            hint="go through BlockPool (scatter_prefill/copy_block) or "
                 "the models/transformer.py paged builders, which take "
                 "per-layer slices — or justify with a pragma")

    def _rule_nns119(self, node: ast.Constant) -> None:
        if self._nns119_exempt:
            return
        if not isinstance(node.value, str):
            return
        if not _HOSTPORT_RE.match(node.value):
            return
        self.emit(
            "NNS119", node,
            f"hard-coded endpoint literal {node.value!r} — fleet "
            f"replicas bind ephemeral ports and move at every deploy, "
            f"so a baked-in host:port pins this code to one replica and "
            f"bypasses discovery, the circuit breaker, and the "
            f"shortest-slack balancer",
            hint="take the endpoint from an element property (servers=/"
                 "operation=), a CLI flag, or a discovery ad "
                 "(query/discovery.py) — or justify with a pragma")

    def _rule_nns114_deque(self, node: ast.Call, dotted: str) -> None:
        if not self._in_obs:
            return
        if not any(_is_obs_record_func(f) for f in self._func_stack):
            return
        if dotted not in ("deque", "collections.deque"):
            return
        # deque(iterable, maxlen) — the 2nd positional IS the bound
        if len(node.args) >= 2 or \
                any(kw.arg == "maxlen" for kw in node.keywords):
            return
        self.emit(
            "NNS114", node,
            "deque() without maxlen built in an obs hot-path recording "
            "function — always-on telemetry records on every frame for "
            "the process lifetime, so an unbounded container here is a "
            "slow leak",
            hint="pass maxlen=<ring capacity>, or justify a "
                 "bounded-by-construction container with a pragma")

    def _rule_nns114_append(self, node: ast.ClassDef) -> None:
        """Flag ``self.x.append/extend(...)`` inside a recording method
        when the class's ``__init__`` bound ``self.x`` to an unbounded
        list or deque."""
        if not self._in_obs:
            return
        unbounded = self._unbounded_init_attrs(node)
        if not unbounded:
            return
        growers = {"append", "appendleft", "extend", "extendleft",
                   "insert"}
        for stmt in node.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _is_obs_record_func(stmt.name):
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in growers and \
                        isinstance(sub.func.value, ast.Attribute) and \
                        isinstance(sub.func.value.value, ast.Name) and \
                        sub.func.value.value.id == "self" and \
                        sub.func.value.attr in unbounded:
                    attr = sub.func.value.attr
                    self.emit(
                        "NNS114", sub,
                        f"{node.name}.{stmt.name}() grows self.{attr}, "
                        f"which __init__ binds unbounded — an obs "
                        f"recording path runs on every frame for the "
                        f"process lifetime, so this container is a slow "
                        f"leak",
                        hint=f"bind self.{attr} to deque(maxlen=...) (or "
                             f"prune at a cap), or justify a bounded-by-"
                             f"construction container with a pragma")

    def _rule_nns116_pack(self, node: ast.Call) -> None:
        """``NAME.pack(...)`` / ``NAME.pack_into(buf, off, ...)`` whose
        value count disagrees with NAME's format field count."""
        if not self._struct_fields:
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("pack", "pack_into")
                and isinstance(func.value, ast.Name)):
            return
        expected = self._struct_fields.get(func.value.id)
        if expected is None:
            return
        if any(isinstance(a, ast.Starred) for a in node.args) \
                or node.keywords:
            return  # dynamic arity: no evidence of a mismatch
        args = node.args[2:] if func.attr == "pack_into" else node.args
        if len(args) == expected:
            return
        self.emit(
            "NNS116", node,
            f"{func.value.id}.{func.attr}() passes {len(args)} value(s) "
            f"but the format declares {expected} field(s) — this wire "
            f"header raises struct.error on the first real frame",
            hint="the format string and its pack/unpack sites evolved "
                 "apart; update whichever side is stale (every site "
                 "must agree with the struct.Struct field count)")

    def _rule_nns116_unpack(self, node: ast.Assign) -> None:
        """``a, b, ... = NAME.unpack[_from](...)`` whose tuple arity
        disagrees with NAME's format field count. A non-tuple target
        (``vals = ...``) or a starred element is dynamic — skipped."""
        if not self._struct_fields:
            return
        value = node.value
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("unpack", "unpack_from")
                and isinstance(value.func.value, ast.Name)):
            return
        expected = self._struct_fields.get(value.func.value.id)
        if expected is None or len(node.targets) != 1:
            return
        target = node.targets[0]
        if not isinstance(target, ast.Tuple) or \
                any(isinstance(e, ast.Starred) for e in target.elts):
            return
        if len(target.elts) == expected:
            return
        self.emit(
            "NNS116", node,
            f"unpacking {value.func.value.id}.{value.func.attr}() into "
            f"{len(target.elts)} name(s) but the format declares "
            f"{expected} field(s) — this wire header raises ValueError "
            f"on the first real frame",
            hint="the format string and its pack/unpack sites evolved "
                 "apart; update whichever side is stale (every site "
                 "must agree with the struct.Struct field count)")

    def _rule_nns115(self, node: ast.ClassDef) -> None:
        """Key drift between a checkpoint save/load pair: the literal
        keys the save method writes vs the keys the load method reads.
        Either side having NO literal keys means a dynamic schema —
        no evidence of drift, so no finding."""
        methods = {stmt.name: stmt for stmt in node.body
                   if isinstance(stmt, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        for save_name, load_name in _CKPT_PAIRS:
            save = methods.get(save_name)
            load = methods.get(load_name)
            if save is None or load is None:
                continue
            written = self._ckpt_keys_written(save)
            read = self._ckpt_keys_read(load)
            if not written or not read:
                continue
            drift = []
            missing = sorted(written - read)
            extra = sorted(read - written)
            if missing:
                drift.append("saved but never restored: "
                             + ", ".join(repr(k) for k in missing))
            if extra:
                drift.append("restored but never saved: "
                             + ", ".join(repr(k) for k in extra))
            if not drift:
                continue
            self.emit(
                "NNS115", save,
                f"{node.name}.{save_name}()/{load_name}() checkpoint "
                f"key sets drift — " + "; ".join(drift),
                hint="make the save-side literal keys and the load-side "
                     "reads symmetric (a saved key the load never reads "
                     "is dead state; a read key the save never writes is "
                     "always absent), or justify an intentional "
                     "asymmetry with a pragma")

    @staticmethod
    def _ckpt_keys_written(func: ast.AST) -> Set[str]:
        """String-literal keys the save method writes: dict-literal
        keys, ``d["k"] = ...`` subscript stores, and ``dict(k=...)``
        keywords."""
        out: Set[str] = set()
        for sub in ast.walk(func):
            if isinstance(sub, ast.Dict):
                for k in sub.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        out.add(k.value)
            elif isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.slice, ast.Constant) and \
                            isinstance(t.slice.value, str):
                        out.add(t.slice.value)
            elif isinstance(sub, ast.Call) and \
                    _dotted(sub.func) == "dict":
                for kw in sub.keywords:
                    if kw.arg:
                        out.add(kw.arg)
        return out

    @staticmethod
    def _ckpt_keys_read(func: ast.AST) -> Set[str]:
        """String-literal keys the load method reads: ``state["k"]``
        subscript loads and ``.get("k")`` / ``.pop("k")`` calls."""
        out: Set[str] = set()
        stored: Set[int] = set()
        for sub in ast.walk(func):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript):
                        stored.add(id(t))
        for sub in ast.walk(func):
            if isinstance(sub, ast.Subscript) and id(sub) not in stored \
                    and isinstance(sub.slice, ast.Constant) and \
                    isinstance(sub.slice.value, str):
                out.add(sub.slice.value)
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("get", "pop") and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                out.add(sub.args[0].value)
        return out

    @staticmethod
    def _unbounded_init_attrs(node: ast.ClassDef) -> Set[str]:
        """Attrs that ``__init__`` binds to ``[]``, ``list()``, or a
        ``deque`` without maxlen."""
        out: Set[str] = set()
        for stmt in node.body:
            if not (isinstance(stmt, ast.FunctionDef)
                    and stmt.name == "__init__"):
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                value = sub.value
                if value is None:
                    continue
                is_unbounded = False
                if isinstance(value, ast.List) and not value.elts:
                    is_unbounded = True
                elif isinstance(value, ast.Call):
                    ctor = _dotted(value.func)
                    if ctor in ("list",) and not value.args:
                        is_unbounded = True
                    elif ctor in ("deque", "collections.deque") and \
                            len(value.args) < 2 and \
                            not any(kw.arg == "maxlen"
                                    for kw in value.keywords):
                        is_unbounded = True
                if not is_unbounded:
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out.add(t.attr)
        return out

    def _enclosing_has_timeout_discipline(self) -> bool:
        """True when the innermost enclosing function visibly bounds its
        socket IO: a ``settimeout(<non-None constant>)`` / ``settimeout(
        <expr>)`` call, or a ``setsockopt`` naming SO_SNDTIMEO /
        SO_RCVTIMEO. Cached per function node — transport hot paths get
        visited once per call expression."""
        if not self._func_nodes:
            return False
        fnode = self._func_nodes[-1]
        cached = self._timeout_discipline.get(id(fnode))
        if cached is not None:
            return cached
        found = False
        for sub in ast.walk(fnode):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "settimeout" and sub.args and \
                    not (isinstance(sub.args[0], ast.Constant)
                         and sub.args[0].value is None):
                found = True
                break
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "setsockopt":
                names = {_dotted(a) for a in sub.args}
                if any(n.endswith(("SO_SNDTIMEO", "SO_RCVTIMEO"))
                       for n in names):
                    found = True
                    break
        self._timeout_discipline[id(fnode)] = found
        return found

    def _rule_nns109(self, node: ast.ClassDef) -> None:
        declares = False
        for stmt in node.body:
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            else:
                continue
            value = stmt.value
            if any(isinstance(t, ast.Name) and t.id == "REORDER_SAFE"
                   for t in targets) and \
                    isinstance(value, ast.Constant) and value.value is True:
                declares = True
                break
        if not declares:
            return
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name in ("chain", "chain_list"):
                for mut, what in self._self_mutations(stmt):
                    self.emit(
                        "NNS109", mut,
                        f"{node.name} declares REORDER_SAFE but its "
                        f"per-frame {stmt.name}() mutates {what} — lane "
                        f"clones processing frames out of order will "
                        f"diverge from the serial element",
                        hint="drop the REORDER_SAFE flag, move the state "
                             "out of the per-frame path, or justify a "
                             "frame-order-independent mutation with a "
                             "pragma")

    @staticmethod
    def _self_mutations(func: ast.AST):
        """(node, description) for each per-frame ``self`` state mutation
        in a chain body: attribute (re)binds (``self.x = ...``,
        ``self.x += ...``), subscript stores (``self.d[k] = ...``), and
        in-place container calls (``self.acc.append(...)``)."""
        mutators = {"append", "extend", "add", "update", "pop", "clear",
                    "insert", "setdefault", "appendleft", "popleft",
                    "remove", "discard"}

        def _is_self_attr(n: ast.AST) -> bool:
            return (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self")

        for sub in ast.walk(func):
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in mutators and \
                    _is_self_attr(sub.func.value):
                yield sub, (f"self.{sub.func.value.attr}"
                            f".{sub.func.attr}(...)")
                continue
            for t in targets:
                if _is_self_attr(t):
                    yield sub, f"self.{t.attr}"
                elif isinstance(t, ast.Subscript) and \
                        _is_self_attr(t.value):
                    yield sub, f"self.{t.value.attr}[...]"

    @staticmethod
    def _touches_buffer_tensors(arg: ast.AST) -> bool:
        """True when the argument expression reads a ``.tensors``
        attribute somewhere (``buf.tensors[0]``, ``info.tensors``...)."""
        return any(isinstance(sub, ast.Attribute) and sub.attr == "tensors"
                   for sub in ast.walk(arg))


def lint_source(text: str, rel: str,
                path: Optional[Path] = None) -> List[Diagnostic]:
    """Lint one Python source string. ``rel`` is the reported source
    label; ``path`` (if given) only feeds the cli.py filename check."""
    path = path or Path(rel)
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Diagnostic("NNS104", ERROR,
                           Location(rel, e.lineno or 1,
                                    (e.offset or 1)),
                           f"file does not parse: {e.msg}")]
    linter = _FileLinter(path, tree, text, rel)
    linter.visit(tree)
    suppressed, missing_reason = _parse_pragmas(text)
    diags = [d for d in linter.diags
             if d.code not in suppressed.get(d.loc.line, set())]
    for lineno in missing_reason:
        diags.append(Diagnostic(
            "NNS199", ERROR, Location(rel, lineno, 1),
            "nns-lint pragma without a justification",
            hint="append ' -- <reason>' explaining why this line is an "
                 "exception"))
    return diags


def lint_file(path: Path, root: Optional[Path] = None) -> List[Diagnostic]:
    rel = str(path.relative_to(root)) if root else str(path)
    return lint_source(path.read_text(encoding="utf-8"), rel, path)


def lint_tree(root: Path) -> List[Diagnostic]:
    """Lint every ``.py`` file under ``root`` (skipping caches)."""
    diags: List[Diagnostic] = []
    base = root if root.is_dir() else root.parent
    files = [root] if root.is_file() else sorted(root.rglob("*.py"))
    for path in files:
        if "__pycache__" in path.parts:
            continue
        diags.extend(lint_file(path, base.parent))
    return sort_diagnostics(diags)
