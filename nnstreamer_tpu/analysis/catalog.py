"""Static element catalog — per-factory metadata for the verifier.

The verifier needs to answer, per factory name and WITHOUT constructing
any pipeline runtime state: which properties exist, how many pads there
are and whether more can be requested, whether the element is a source or
a sink, which media types its sink side accepts, and — where statically
derivable — what caps its src side produces. This module derives that
once per element class:

- properties come from the class ``PROPERTIES`` dict merged across the
  MRO (exactly how ``Element.__init__`` builds its property table);
- pad topology comes from instantiating the class once behind a guard —
  element constructors only allocate pads and plain host objects (threads
  and backends appear at ``start()``), so this stays purely structural;
  a constructor that needs more context degrades to "unknown pads";
- request-pad capability is read off the class: an element that overrides
  ``request_src_pad``/``request_sink_pad`` can grow pads on demand;
- accepted input media types and static source caps are small hand-kept
  tables for the built-in factories (a subplugin absent from the tables
  simply opts out of media-type checking — never a false positive).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, Optional

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.registry import ELEMENT, get_subplugin

#: media-type names used by the built-in elements (tensors/types.py)
TENSORS = "other/tensors"
TENSOR = "other/tensor"
_TENSOR_IN: FrozenSet[str] = frozenset({TENSORS, TENSOR})

#: factories whose sink side only accepts the listed media types.
#: Factories not listed accept anything (their checks are skipped).
MEDIA_IN: Dict[str, FrozenSet[str]] = {
    "tensor_converter": frozenset({"video/x-raw", "audio/x-raw",
                                   "application/octet-stream",
                                   "text/x-raw"}),
    "tensor_filter": _TENSOR_IN,
    "tensor_decoder": _TENSOR_IN,
    "tensor_transform": _TENSOR_IN,
    "tensor_mux": _TENSOR_IN,
    "tensor_merge": _TENSOR_IN,
    "tensor_demux": _TENSOR_IN,
    "tensor_split": _TENSOR_IN,
    "tensor_crop": _TENSOR_IN,
    "tensor_aggregator": _TENSOR_IN,
    "tensor_rate": _TENSOR_IN,
    "tensor_if": _TENSOR_IN,
    "tensor_sparse_enc": _TENSOR_IN,
    "tensor_sparse_dec": _TENSOR_IN,
    "tensor_quant_enc": _TENSOR_IN,
    "tensor_quant_dec": _TENSOR_IN,
    "tensor_reposink": _TENSOR_IN,
    "tensor_query_client": _TENSOR_IN,
    "tensor_query_serversink": _TENSOR_IN,
}

#: elements that forward caps unchanged — propagation flows through them
PASSTHROUGH: FrozenSet[str] = frozenset({"queue", "tee"})


@dataclasses.dataclass(frozen=True)
class ElementSpec:
    """Statically-derived facts about one element factory."""

    factory: str
    klass: type
    properties: FrozenSet[str]        # underscore-normalized names
    n_sink: Optional[int]             # None = unknown (ctor not probeable)
    n_src: Optional[int]
    requests_sink: bool
    requests_src: bool
    is_source: bool                   # runs a streaming thread
    media_in: Optional[FrozenSet[str]]  # None = accepts anything

    @property
    def is_sink(self) -> bool:
        """No outputs at all: a terminal element."""
        return self.n_src == 0 and not self.requests_src


_spec_cache: Dict[str, Optional[ElementSpec]] = {}


def spec_for(factory: str) -> Optional[ElementSpec]:
    """Spec for a factory name, or None when the factory is unknown."""
    if factory in _spec_cache:
        return _spec_cache[factory]
    cls = get_subplugin(ELEMENT, factory)
    spec = _derive(factory, cls) if isinstance(cls, type) else None
    _spec_cache[factory] = spec
    return spec


def _derive(factory: str, cls: type) -> ElementSpec:
    props: Dict[str, Any] = {}
    for klass in reversed(cls.__mro__):
        props.update(getattr(klass, "PROPERTIES", {}))

    n_sink: Optional[int] = None
    n_src: Optional[int] = None
    try:
        inst = cls()
        n_sink, n_src = len(inst.sinkpads), len(inst.srcpads)
    except Exception:  # nns-lint: disable=NNS104 -- ctor probe: any failure just means pad counts stay unknown
        pass

    from nnstreamer_tpu.pipeline.pipeline import SourceElement

    return ElementSpec(
        factory=factory,
        klass=cls,
        properties=frozenset(k.replace("-", "_") for k in props),
        n_sink=n_sink,
        n_src=n_src,
        requests_sink=(cls.request_sink_pad is not Element.request_sink_pad),
        requests_src=(cls.request_src_pad is not Element.request_src_pad),
        is_source=issubclass(cls, SourceElement),
        media_in=MEDIA_IN.get(factory),
    )


def _prop(props: Dict[str, str], spec: ElementSpec, key: str) -> Any:
    """Property value for caps derivation: explicit value, else default."""
    if key in props:
        return props[key]
    defaults: Dict[str, Any] = {}
    for klass in reversed(spec.klass.__mro__):
        defaults.update(getattr(klass, "PROPERTIES", {}))
    return defaults.get(key)


def static_src_caps(spec: ElementSpec,
                    props: Dict[str, str]) -> Optional[Caps]:
    """Source-element output caps derivable from properties alone, or
    None when the format only settles at runtime (appsrc without caps,
    repo/query sources, ...). Mirrors each source's ``negotiate()``."""
    f = spec.factory
    if f == "videotestsrc":
        try:
            return Caps("video/x-raw", {
                "format": str(_prop(props, spec, "format")),
                "width": int(_prop(props, spec, "width")),
                "height": int(_prop(props, spec, "height")),
                "framerate": str(_prop(props, spec, "framerate")),
            })
        except (TypeError, ValueError):
            return None
    if f == "audiotestsrc":
        try:
            return Caps("audio/x-raw", {
                "format": str(_prop(props, spec, "format")),
                "rate": int(_prop(props, spec, "rate")),
                "channels": int(_prop(props, spec, "channels")),
            })
        except (TypeError, ValueError):
            return None
    if f == "filesrc":
        return Caps("application/octet-stream", {})
    if f in ("multifilesrc", "appsrc"):
        caps = props.get("caps")
        if caps:
            from nnstreamer_tpu.pipeline.parse import parse_caps_string

            try:
                return parse_caps_string(caps)
            except ValueError:
                return None
        return (Caps("application/octet-stream", {})
                if f == "multifilesrc" else None)
    if f == "tensor_src_iio":
        return Caps(TENSORS, {})
    return None
