"""Whole-program concurrency analysis — the ``NNS2xx`` half of
``nns-lint --concurrency``.

The streaming graph is aggressively threaded (ingest lanes, the EDF
scheduler, the dispatch window, transport workers, the flight recorder)
and now guards its shared state with 35+ locks across 15 modules. Every
concurrency bug so far was found by luck or by a chaos smoke after the
fact; these rules make the lock discipline *checkable*:

- NNS201: **guarded-attribute inference.** For each class, infer which
  attributes the code itself treats as lock-guarded — attributes
  mutated inside ``with self._lock:`` blocks — then flag mutations (and,
  with strong evidence, reads) of a guarded attribute outside the lock.
  A method whose name ends in ``_locked`` is assumed to be called with
  the lock held (the codebase's own convention).
- NNS202: **static lock-ordering graph.** Every nested ``with``
  acquisition (and every call made under a lock to a same-file function
  that acquires locks, propagated to a fixpoint) contributes an edge
  ``outer → inner`` to one project-wide digraph. A cycle in that graph
  is a potential deadlock: two threads taking the same locks in
  opposite orders. The graph is also exported (:func:`static_lock_graph`)
  so the runtime witness (``obs/lockgraph.py``) can cross-check the
  orders it actually observes against the orders the code promises.
- NNS203: **check-then-act races.** ``if k in self.d: ... self.d[k]``
  with no lock held, on an attribute the class mutates under a lock
  elsewhere — the membership test and the mutation are two separate
  critical sections, so another thread can interleave between them.
- NNS204: **foreign calls under lock.** Invoking a callback / hook /
  fn-gauge, or posting to the pipeline bus, while holding a subsystem
  lock: the callee is outside this subsystem's control and may call
  back into it (or block), which is the classic reentrancy-deadlock
  shape. Copy what the callee needs under the lock, call it outside.

Findings are suppressed per line with the same pragma as the NNS1xx
rules (``# nns-lint: disable=NNS202 -- <why>``). NNS199 (reasonless
pragma) stays the AST lint's finding so running both passes never
duplicates it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from nnstreamer_tpu.analysis.astlint import _parse_pragmas
from nnstreamer_tpu.analysis.diagnostics import (
    ERROR,
    Diagnostic,
    Location,
    sort_diagnostics,
)

#: constructors whose result IS a lock (kind recorded for RLock
#: reentrancy and for the runtime witness's node metadata)
_LOCK_CTORS = {"threading.Lock": "lock", "threading.RLock": "rlock",
               "threading.Condition": "condition",
               "Lock": "lock", "RLock": "rlock", "Condition": "condition"}
#: constructors whose result is thread-safe by construction — attributes
#: bound to these are exempt from NNS201 (their methods synchronize
#: internally, so "mutations" of them need no class lock)
_SYNC_SAFE_CTORS = {"threading.Event", "threading.Semaphore",
                    "threading.BoundedSemaphore",
                    "threading.Barrier", "threading.local",
                    "queue.Queue", "queue.PriorityQueue",
                    "queue.LifoQueue", "queue.SimpleQueue",
                    "Event", "Semaphore", "local"}
#: registry constructor methods — metric objects carry their own lock
_METRIC_CTOR_ATTRS = {"counter", "gauge", "histogram"}

#: in-place container mutators (same family NNS109 tracks)
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "update", "setdefault", "pop", "popleft", "popitem",
             "remove", "discard", "clear", "sort", "reverse",
             "move_to_end"}
#: dict/container mutators relevant to the check-then-act window
_CTA_MUTATORS = {"pop", "popitem", "update", "setdefault", "clear",
                 "append", "add", "remove", "discard", "insert",
                 "move_to_end"}

#: callback-shaped names: invoking one of these while holding a lock is
#: handing control to code outside the subsystem (NNS204)
_CB_NAME_RE = re.compile(
    r"(?:^|_)(?:cb|cbs|callback|callbacks|hook|hooks|fn|fns|listener|"
    r"listeners|notifier|subscriber|subscribers)$|^on_[a-z0-9_]+$")
#: pipeline-bus entry points — posting re-enters the bus's own lock and
#: wakes arbitrary waiters, so it must happen outside subsystem locks
_BUS_POST_ATTRS = {"post_error", "post_message", "post_warning"}

#: methods whose accesses never count for NNS201: construction/teardown
#: runs before (or after) the object is shared, repr/str are debug
#: surfaces, and lifecycle transitions (start/stop) are phase-separated
#: from steady-state — e.g. a drain loop that owns its state unlocked
#: while running, with stop() joining the thread before touching it
#: (the serving engine), must not have stop()'s defensive locking read
#: as "this attribute is lock-guarded". NNS202/NNS204 still see these
#: methods — a lock-order cycle in stop() is a real deadlock.
_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__repr__",
                   "__str__", "__enter__", "__exit__",
                   "start", "stop", "close", "shutdown"}

#: the assumed-guard token for ``*_locked`` helper methods: satisfies
#: "some lock is held" for any of the class's locks
_ASSUMED = ("assumed",)

LockId = Tuple[str, ...]


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def lock_display(lock: LockId) -> str:
    """Stable human/JSON name for a lock node."""
    if lock[0] == "attr":
        return f"{lock[1]}:{lock[2]}.{lock[3]}"
    if lock[0] == "mod":
        return f"{lock[1]}:{lock[2]}"
    if lock[0] == "local":
        return f"{lock[1]}:{lock[2]}:{lock[3]}"
    return "<assumed>"


class _Access:
    """One touch of ``self.<attr>``: where, how, and under what locks."""

    __slots__ = ("kind", "method", "node", "held", "in_nested")

    def __init__(self, kind: str, method: str, node: ast.AST,
                 held: frozenset, in_nested: bool):
        self.kind = kind              # "read" | "write"
        self.method = method
        self.node = node
        self.held = held
        self.in_nested = in_nested


class _ClassFacts:
    def __init__(self, rel: str, node: ast.ClassDef):
        self.rel = rel
        self.node = node
        self.name = node.name
        self.lock_attrs: Dict[str, str] = {}       # attr -> kind
        self.sync_safe_attrs: Set[str] = set()
        self.accesses: Dict[str, List[_Access]] = {}
        self.methods: Dict[str, ast.AST] = {}


class _FuncFacts:
    def __init__(self, key: Tuple, node: ast.AST):
        self.key = key                # ("meth", class, name) | ("func", name)
        self.node = node
        self.acquires: Set[LockId] = set()
        #: calls to same-file callables: (callee key, held set, node)
        self.calls: List[Tuple[Tuple, frozenset, ast.AST]] = []


def _modkey(rel: str) -> str:
    """Dotted module name for a repo-relative path — the cross-file
    identity of module-level locks (``from mod import THE_LOCK`` must
    resolve to the same graph node as the defining module's uses)."""
    key = rel[:-3] if rel.endswith(".py") else rel
    key = key.replace("/", ".").replace("\\", ".")
    return key[:-9] if key.endswith(".__init__") else key


class _FileModel:
    """Per-file facts feeding the whole-program passes."""

    def __init__(self, rel: str, tree: ast.Module, text: str):
        self.rel = rel
        self.modkey = _modkey(rel)
        self.tree = tree
        self.text = text
        self.classes: Dict[str, _ClassFacts] = {}
        self.module_locks: Dict[str, str] = {}     # name -> kind
        self.imports: Dict[str, str] = {}          # bound name -> module
        #: ``from mod import name [as alias]``: alias -> (module, name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.funcs: Dict[Tuple, _FuncFacts] = {}
        #: lock creation sites: "rel:line" -> LockId (for the runtime
        #: witness's site → symbolic-name mapping)
        self.lock_sites: Dict[str, LockId] = {}
        #: acquisition-order edges: (outer, inner) -> "rel:line"
        self.edges: Dict[Tuple[LockId, LockId], str] = {}
        #: NNS203 candidates: (test node, mutation node, class, attr)
        self.check_then_act: List[Tuple[ast.AST, ast.AST, str, str]] = []
        #: NNS204 candidates: (call node, description, lock)
        self.foreign_calls: List[Tuple[ast.AST, str, LockId]] = []


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        return _LOCK_CTORS.get(_dotted(value.func))
    return None


def _sync_safe_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = _dotted(value.func)
    if d in _SYNC_SAFE_CTORS:
        return True
    return (isinstance(value.func, ast.Attribute)
            and value.func.attr in _METRIC_CTOR_ATTRS)


def _collect_class_decls(cf: _ClassFacts) -> None:
    """First pass over a class: which attrs are locks, which are
    thread-safe by construction."""
    for sub in ast.walk(cf.node):
        if not isinstance(sub, ast.Assign):
            continue
        kind = _lock_ctor_kind(sub.value)
        safe = _sync_safe_ctor(sub.value)
        if kind is None and not safe:
            continue
        for t in sub.targets:
            if _is_self_attr(t):
                if kind is not None:
                    cf.lock_attrs[t.attr] = kind
                else:
                    cf.sync_safe_attrs.add(t.attr)


class _FuncWalker:
    """Walks one function body tracking the held-lock context, recording
    attribute accesses, acquisition edges, same-file calls, NNS203/204
    candidates."""

    def __init__(self, model: _FileModel, cf: Optional[_ClassFacts],
                 method: str, ff: _FuncFacts, assumed_locked: bool):
        self.model = model
        self.cf = cf
        self.method = method
        self.ff = ff
        self.held: List[LockId] = [_ASSUMED] if assumed_locked else []
        self.nesting = 0              # inside a nested def/lambda
        #: local aliases: name -> LockId (wlock = self._wlocks[...])
        self.aliases: Dict[str, LockId] = {}

    # -- lock identification -------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[LockId]:
        if isinstance(expr, ast.Call):
            expr = expr.func            # with self._lock.something(): — no
        if _is_self_attr(expr) and self.cf is not None:
            attr = expr.attr
            if attr in self.cf.lock_attrs or "lock" in attr.lower():
                self.cf.lock_attrs.setdefault(attr, "lock")
                return ("attr", self.model.rel, self.cf.name, attr)
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.aliases:
                return self.aliases[name]
            if name in self.model.module_locks:
                return ("mod", self.model.modkey, name)
            if name in self.model.from_imports and "lock" in name.lower():
                mod, orig = self.model.from_imports[name]
                return ("mod", mod, orig)
            if "lock" in name.lower():
                return ("local", self.model.rel, self.method, name)
        if isinstance(expr, ast.Attribute):
            d = _dotted(expr)
            if d and "lock" in expr.attr.lower():
                base = d.split(".", 1)[0]
                if base in self.model.imports:
                    # mod.THE_LOCK through a plain `import mod`
                    return ("mod", self.model.imports[base], expr.attr)
                # CLS._SERVERS_LOCK and friends: class-level named
                # locks, keyed by bare name (matches the creation site)
                return ("mod", self.model.modkey, expr.attr)
        return None

    def _alias_target(self, value: ast.AST) -> Optional[LockId]:
        """``wlock = self._wlocks.setdefault(conn, Lock())`` /
        ``wlock = self._wlocks[sock]`` — a per-key lock drawn from a
        self container; keyed as ``Class.<attr>[]``."""
        if self.cf is None:
            return None
        for sub in ast.walk(value):
            if _is_self_attr(sub) and "lock" in sub.attr.lower():
                return ("attr", self.model.rel, self.cf.name,
                        sub.attr + "[]")
        return None

    # -- recording -----------------------------------------------------------
    def _held_set(self) -> frozenset:
        return frozenset(self.held)

    def _record_access(self, attr: str, kind: str, node: ast.AST) -> None:
        cf = self.cf
        if cf is None:
            return
        if attr in cf.lock_attrs or attr in cf.sync_safe_attrs:
            return
        cf.accesses.setdefault(attr, []).append(_Access(
            kind, self.method, node, self._held_set(),
            self.nesting > 0))

    def _record_acquire(self, lock: LockId, node: ast.AST) -> None:
        self.ff.acquires.add(lock)
        site = f"{self.model.rel}:{getattr(node, 'lineno', 1)}"
        for outer in self.held:
            if outer == _ASSUMED:
                continue
            # outer == lock IS recorded: a non-reentrant self-nest is
            # the most immediate deadlock there is (NNS202 exempts
            # RLock self-loops by kind)
            self.model.edges.setdefault((outer, lock), site)

    # -- traversal -----------------------------------------------------------
    def walk_body(self, body: Iterable[ast.AST]) -> None:
        for stmt in body:
            self.visit(stmt)

    def visit(self, node: ast.AST) -> None:
        meth = getattr(self, f"_visit_{type(node).__name__}", None)
        if meth is not None:
            meth(node)
            return
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _visit_With(self, node: ast.With) -> None:
        acquired: List[LockId] = []
        for item in node.items:
            self.visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self._record_acquire(lock, item.context_expr)
                self.held.append(lock)
                acquired.append(lock)
        self.walk_body(node.body)
        for _ in acquired:
            self.held.pop()

    _visit_AsyncWith = _visit_With  # type: ignore[assignment]

    def _visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def runs later, on whatever thread calls it — its
        # body is NOT under the enclosing with; record accesses with an
        # empty held set and the in_nested marker
        saved_held, saved_nesting = self.held, self.nesting
        self.held, self.nesting = [], saved_nesting + 1
        self.walk_body(node.body)
        self.held, self.nesting = saved_held, saved_nesting

    _visit_AsyncFunctionDef = _visit_FunctionDef  # type: ignore[assignment]

    def _visit_Lambda(self, node: ast.Lambda) -> None:
        saved_held, saved_nesting = self.held, self.nesting
        self.held, self.nesting = [], saved_nesting + 1
        self.visit(node.body)
        self.held, self.nesting = saved_held, saved_nesting

    def _visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for t in node.targets:
            self._visit_store_target(t)
        # local lock aliases for later `with wlock:` blocks
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            alias = self._alias_target(node.value)
            if alias is not None:
                self.aliases[node.targets[0].id] = alias

    def _visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        self._visit_store_target(node.target, aug=True)

    def _visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._visit_store_target(node.target)

    def _visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if _is_self_attr(t):
                self._record_access(t.attr, "write", t)
            elif isinstance(t, ast.Subscript) and _is_self_attr(t.value):
                self._record_access(t.value.attr, "write", t)
                self.visit(t.slice)

    def _visit_store_target(self, t: ast.AST, aug: bool = False) -> None:
        if _is_self_attr(t):
            self._record_access(t.attr, "write", t)
        elif isinstance(t, ast.Subscript):
            if _is_self_attr(t.value):
                self._record_access(t.value.attr, "write", t)
            else:
                self.visit(t.value)
            self.visit(t.slice)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._visit_store_target(e)

    def _visit_Attribute(self, node: ast.Attribute) -> None:
        if _is_self_attr(node) and isinstance(node.ctx, ast.Load):
            self._record_access(node.attr, "read", node)
        else:
            self.visit(node.value)

    def _visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # self.X.append(...) — in-place mutation of self.X
        if isinstance(func, ast.Attribute) and \
                func.attr in _MUTATORS and _is_self_attr(func.value):
            self._record_access(func.value.attr, "write", node)
        else:
            self.visit(func)
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)
        self._note_call(node)
        self._check_foreign_call(node)

    def _note_call(self, node: ast.Call) -> None:
        """Same-file callee resolution for the interprocedural
        lock-acquisition closure (NNS202)."""
        func = node.func
        callee: Optional[Tuple] = None
        if _is_self_attr(func) and self.cf is not None:
            callee = ("meth", self.cf.name, func.attr)
        elif isinstance(func, ast.Name):
            callee = ("func", func.id)
        if callee is not None:
            self.ff.calls.append((callee, self._held_set(), node))

    def _check_foreign_call(self, node: ast.Call) -> None:
        held = [h for h in self.held if h != _ASSUMED]
        if not held:
            return
        func = node.func
        what: Optional[str] = None
        if isinstance(func, ast.Name) and _CB_NAME_RE.search(func.id):
            what = f"{func.id}(...)"
        elif isinstance(func, ast.Attribute):
            if func.attr in _BUS_POST_ATTRS:
                what = f".{func.attr}(...) (pipeline bus)"
            elif _is_self_attr(func) and _CB_NAME_RE.search(func.attr):
                what = f"self.{func.attr}(...)"
            elif _is_self_attr(func.value) and \
                    _CB_NAME_RE.search(func.value.attr) and \
                    func.attr not in _MUTATORS and \
                    func.attr not in ("copy", "index", "count", "get",
                                      "keys", "values", "items"):
                # maintaining the callback registry (append/remove/copy)
                # under the lock is correct practice — only *invoking* a
                # member hands control outside the subsystem
                what = f"self.{func.value.attr}.{func.attr}(...)"
        if what is not None:
            self.model.foreign_calls.append((node, what, held[-1]))

    def _visit_If(self, node: ast.If) -> None:
        self._check_then_act(node)
        self.visit(node.test)
        self.walk_body(node.body)
        self.walk_body(node.orelse)

    def _check_then_act(self, node: ast.If) -> None:
        """``if k in self.d:`` (no lock) followed in either branch by an
        unguarded mutation of ``self.d`` — recorded as a candidate; the
        whole-program pass keeps it only when the class mutates the attr
        under a lock elsewhere."""
        if self.cf is None or self.held:
            return
        test = node.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test = test.operand
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.In, ast.NotIn))
                and _is_self_attr(test.comparators[0])):
            return
        attr = test.comparators[0].attr
        if attr in self.cf.lock_attrs or attr in self.cf.sync_safe_attrs:
            return
        for stmt in (*node.body, *node.orelse):
            mut = self._find_unguarded_mutation(stmt, attr)
            if mut is not None:
                self.model.check_then_act.append(
                    (node, mut, self.cf.name, attr))
                return

    def _find_unguarded_mutation(self, stmt: ast.AST,
                                 attr: str) -> Optional[ast.AST]:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.With):
                return None     # branch re-locks before mutating: fine
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            _is_self_attr(t.value) and \
                            t.value.attr == attr:
                        return sub
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            _is_self_attr(t.value) and \
                            t.value.attr == attr:
                        return sub
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _CTA_MUTATORS and \
                    _is_self_attr(sub.func.value) and \
                    sub.func.value.attr == attr:
                return sub
        return None


def _analyze_file(rel: str, text: str) -> Optional[_FileModel]:
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None         # the AST lint already reports unparseable files
    model = _FileModel(rel, tree, text)

    # imports (cross-file identity of module locks) + module-level
    # locks and their creation sites
    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                bound = alias.asname or alias.name.split(".")[0]
                model.imports[bound] = alias.name if alias.asname \
                    else alias.name.split(".")[0]
        elif isinstance(stmt, ast.ImportFrom):
            parts = model.modkey.split(".")
            if stmt.level > 0:
                base = parts[:len(parts) - stmt.level]
                mod = ".".join(base + ([stmt.module] if stmt.module
                                       else []))
            else:
                mod = stmt.module or ""
            for alias in stmt.names:
                model.from_imports[alias.asname or alias.name] = \
                    (mod, alias.name)
        elif isinstance(stmt, ast.Assign):
            kind = _lock_ctor_kind(stmt.value)
            if kind is not None:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        model.module_locks[t.id] = kind
                        model.lock_sites[f"{rel}:{stmt.value.lineno}"] = \
                            ("mod", model.modkey, t.id)

    # classes: decls first (lock attrs usable from any method)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cf = _ClassFacts(rel, node)
            _collect_class_decls(cf)
            model.classes[node.name] = cf
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    kind = _lock_ctor_kind(sub.value)
                    if kind is None:
                        continue
                    for t in sub.targets:
                        if _is_self_attr(t):
                            model.lock_sites[
                                f"{rel}:{sub.value.lineno}"] = \
                                ("attr", rel, node.name, t.attr)
                        elif isinstance(t, ast.Name):
                            # class-level lock (shared across instances)
                            model.lock_sites[
                                f"{rel}:{sub.value.lineno}"] = \
                                ("mod", model.modkey, t.id)
                            model.module_locks.setdefault(t.id, kind)

    # walk every function with held-lock context
    def walk_func(fnode: ast.AST, cf: Optional[_ClassFacts],
                  key: Tuple) -> None:
        ff = _FuncFacts(key, fnode)
        model.funcs[key] = ff
        assumed = cf is not None and fnode.name.endswith("_locked")
        w = _FuncWalker(model, cf, fnode.name, ff, assumed)
        w.walk_body(fnode.body)

    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_func(stmt, None, ("func", stmt.name))
    for cname, cf in model.classes.items():
        for stmt in cf.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cf.methods[stmt.name] = stmt
                walk_func(stmt, cf, ("meth", cname, stmt.name))
    return model


# -- whole-program passes ----------------------------------------------------

def _propagate_acquires(model: _FileModel) -> Dict[Tuple, Set[LockId]]:
    """May-acquire closure per function over same-file calls (fixpoint),
    then fold call-under-lock edges into the model's edge set."""
    may: Dict[Tuple, Set[LockId]] = {
        k: set(f.acquires) for k, f in model.funcs.items()}
    changed = True
    while changed:
        changed = False
        for key, ff in model.funcs.items():
            for callee, _held, _node in ff.calls:
                target = may.get(callee)
                if target and not target <= may[key]:
                    may[key] |= target
                    changed = True
    for ff in model.funcs.values():
        for callee, held, node in ff.calls:
            target = may.get(callee)
            if not target or not held:
                continue
            site = f"{model.rel}:{getattr(node, 'lineno', 1)}"
            for outer in held:
                if outer == _ASSUMED:
                    continue
                for inner in target:
                    if inner != outer:
                        model.edges.setdefault((outer, inner), site)
    return may


def _held_on_entry(model: _FileModel, cname: str) -> Set[str]:
    """Methods that run with the class lock already held: the
    ``*_locked`` naming convention, plus any method whose EVERY
    same-file call site holds a lock (or sits inside another
    held-on-entry method) — private helpers factored out of critical
    sections. One externally-reachable or unlocked call site
    disqualifies; call sites in ``__init__``-style methods are neutral
    (construction is single-threaded)."""
    call_sites: Dict[str, List[Tuple[Tuple, frozenset]]] = {}
    for key, ff in model.funcs.items():
        for callee, held, _node in ff.calls:
            if callee[0] == "meth" and callee[1] == cname:
                call_sites.setdefault(callee[2], []).append((key, held))
    assumed = {name for name in model.classes[cname].methods
               if name.endswith("_locked")}
    changed = True
    while changed:
        changed = False
        for meth, sites in call_sites.items():
            if meth in assumed or meth in _EXEMPT_METHODS:
                continue
            countable = [
                (k, h) for (k, h) in sites
                if not (k[0] == "meth" and k[1] == cname
                        and k[2] in _EXEMPT_METHODS)]
            if countable and all(
                    h or (k[0] == "meth" and k[1] == cname
                          and k[2] in assumed)
                    for k, h in countable):
                assumed.add(meth)
                changed = True
    return assumed


def _nns201(model: _FileModel, diags: List[Diagnostic]) -> None:
    for cf in model.classes.values():
        if not cf.lock_attrs:
            continue
        assumed = _held_on_entry(model, cf.name)
        for attr, accesses in cf.accesses.items():
            flaggable = [a for a in accesses
                         if a.method not in _EXEMPT_METHODS]
            locked = [a for a in flaggable
                      if a.held or a.method in assumed]
            unlocked = [a for a in flaggable
                        if not a.held and a.method not in assumed]
            locked_writes = [a for a in locked if a.kind == "write"]
            if not locked_writes or not unlocked:
                continue
            # dominant guard: the lock named in most locked accesses
            # (reported so the fix is obvious)
            counts: Dict[LockId, int] = {}
            for a in locked:
                for lk in a.held:
                    if lk != _ASSUMED:
                        counts[lk] = counts.get(lk, 0) + 1
            guard = max(counts, key=counts.get) if counts else None
            guard_name = lock_display(guard) if guard else "its lock"
            for a in unlocked:
                if a.kind == "write":
                    diags.append(Diagnostic(
                        "NNS201", ERROR,
                        Location(model.rel, a.node.lineno,
                                 a.node.col_offset + 1),
                        f"{cf.name}.{a.method}() mutates self.{attr} "
                        f"outside the lock — the class guards this "
                        f"attribute with {guard_name} everywhere else, "
                        f"so this write races every locked reader/"
                        f"writer",
                        hint="take the lock around the mutation, or "
                             "justify a single-threaded phase with a "
                             "pragma"))
            # reads: flagged only on strong evidence that the class
            # treats reads as needing the lock too — every OTHER access
            # is locked (reads included) and there are enough of them
            # to call it a discipline rather than a coincidence
            unlocked_reads = [a for a in unlocked if a.kind == "read"]
            locked_reads = [a for a in locked if a.kind == "read"]
            if unlocked_reads and not [a for a in unlocked
                                       if a.kind == "write"] and \
                    locked_reads and len(locked) >= 3 and \
                    len(unlocked_reads) <= 2:
                for a in unlocked_reads:
                    diags.append(Diagnostic(
                        "NNS201", ERROR,
                        Location(model.rel, a.node.lineno,
                                 a.node.col_offset + 1),
                        f"{cf.name}.{a.method}() reads self.{attr} "
                        f"outside the lock — every other access in "
                        f"this class (reads included) holds "
                        f"{guard_name}, so this read can observe a "
                        f"torn/stale value",
                        hint="copy the value under the lock, or "
                             "justify a racy read (e.g. a monotonic "
                             "flag) with a pragma"))


def _nns203(model: _FileModel, diags: List[Diagnostic]) -> None:
    for test, mut, cname, attr in model.check_then_act:
        cf = model.classes[cname]
        accesses = cf.accesses.get(attr, ())
        if not any(a.kind == "write" and a.held for a in accesses):
            continue    # no evidence the attr is shared lock-guarded state
        diags.append(Diagnostic(
            "NNS203", ERROR,
            Location(model.rel, test.lineno, test.col_offset + 1),
            f"check-then-act race on self.{attr}: the membership test "
            f"(line {test.lineno}) and the mutation (line "
            f"{mut.lineno}) are separate critical sections — "
            f"{cname} mutates self.{attr} under a lock elsewhere, so "
            f"another thread can interleave between test and act",
            hint="hold the lock across the test AND the mutation, or "
                 "use an atomic form (setdefault/pop(k, None)), or "
                 "justify single-threaded use with a pragma"))


def _nns204(model: _FileModel, diags: List[Diagnostic]) -> None:
    for node, what, lock in model.foreign_calls:
        diags.append(Diagnostic(
            "NNS204", ERROR,
            Location(model.rel, node.lineno, node.col_offset + 1),
            f"foreign call {what} while holding "
            f"{lock_display(lock)} — the callee is outside this "
            f"subsystem's control and may block or re-enter the lock "
            f"(reentrancy-deadlock shape)",
            hint="copy what the callee needs under the lock, invoke it "
                 "after release, or justify a known-leaf callee with a "
                 "pragma"))


def _find_cycles(edges: Dict[Tuple[LockId, LockId], str],
                 lock_kinds: Dict[LockId, str]
                 ) -> List[Tuple[List[LockId], List[str]]]:
    """Strongly connected components of the acquisition-order digraph;
    each SCC with >1 lock (or a non-reentrant self-loop) is a potential
    deadlock. Returns (cycle locks, example edge sites)."""
    adj: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())

    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    sccs: List[List[LockId]] = []
    counter = [0]

    def strongconnect(v: LockId) -> None:
        # iterative Tarjan — analysis inputs are user code, recursion
        # depth must not depend on their lock count
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    out: List[Tuple[List[LockId], List[str]]] = []
    for scc in sccs:
        members = set(scc)
        if len(scc) > 1:
            sites = sorted({site for (a, b), site in edges.items()
                            if a in members and b in members})
            out.append((sorted(scc), sites))
    # non-reentrant self-loops (with self._lock: ... with self._lock:)
    for (a, b), site in sorted(edges.items(), key=lambda kv: kv[1]):
        if a == b and lock_kinds.get(a, "lock") != "rlock":
            out.append(([a], [site]))
    return out


def _site_loc(site: str) -> Location:
    rel, _, line = site.rpartition(":")
    return Location(rel, int(line) if line.isdigit() else 1, 1)


def _nns202(models: List[_FileModel], diags: List[Diagnostic]) -> None:
    edges: Dict[Tuple[LockId, LockId], str] = {}
    kinds: Dict[LockId, str] = {}
    for m in models:
        for key, site in m.edges.items():
            edges.setdefault(key, site)
        for site, lock in m.lock_sites.items():
            if lock[0] == "mod":
                kinds[lock] = m.module_locks.get(lock[2], "lock")
        for cf in m.classes.values():
            for attr, kind in cf.lock_attrs.items():
                kinds[("attr", m.rel, cf.name, attr)] = kind
    for cycle, sites in _find_cycles(edges, kinds):
        names = " -> ".join(lock_display(c) for c in cycle)
        if len(cycle) == 1:
            msg = (f"non-reentrant lock {lock_display(cycle[0])} "
                   f"acquired while already held — this path "
                   f"self-deadlocks the moment it runs")
        else:
            msg = (f"lock-order cycle: {names} — two threads taking "
                   f"these locks in opposite orders deadlock; "
                   f"acquisition sites: {', '.join(sites[:4])}")
        diags.append(Diagnostic(
            "NNS202", ERROR, _site_loc(sites[0]), msg,
            hint="pick ONE global order for these locks and make every "
                 "path acquire in that order (or collapse them into "
                 "one lock); justify a phase-separated exception with "
                 "a pragma"))


# -- public API --------------------------------------------------------------

def _iter_sources(root: Path) -> List[Tuple[str, str, Path]]:
    base = root if root.is_dir() else root.parent
    files = [root] if root.is_file() else sorted(root.rglob("*.py"))
    out = []
    for path in files:
        if "__pycache__" in path.parts:
            continue
        rel = str(path.relative_to(base.parent))
        out.append((rel, path.read_text(encoding="utf-8"), path))
    return out


def lint_concurrency_sources(sources: Dict[str, str]) -> List[Diagnostic]:
    """Run the NNS2xx pass over in-memory sources (``rel -> text``).
    The whole-program passes (NNS202's graph, NNS201's class facts) see
    exactly the given set of files — the test-fixture entry point."""
    models: List[_FileModel] = []
    for rel, text in sorted(sources.items()):
        m = _analyze_file(rel, text)
        if m is not None:
            models.append(m)
    diags: List[Diagnostic] = []
    for m in models:
        _propagate_acquires(m)
    for m in models:
        _nns201(m, diags)
        _nns203(m, diags)
        _nns204(m, diags)
    _nns202(models, diags)
    # per-file pragma suppression (reasonless pragmas stay NNS199,
    # emitted by the AST lint so the two passes never double-report)
    suppressed: Dict[str, Dict[int, Set[str]]] = {}
    for rel, text in sources.items():
        suppressed[rel], _ = _parse_pragmas(text)
    out = [d for d in diags
           if d.code not in suppressed.get(d.loc.source, {})
           .get(d.loc.line, set())]
    return sort_diagnostics(out)


def lint_concurrency_source(text: str, rel: str = "x.py"
                            ) -> List[Diagnostic]:
    """Single-source convenience wrapper (fixtures, docs examples)."""
    return lint_concurrency_sources({rel: text})


def lint_concurrency(root: Path) -> List[Diagnostic]:
    """Run the whole-program concurrency pass over every ``.py`` file
    under ``root`` (a package dir or a single file)."""
    return lint_concurrency_sources(
        {rel: text for rel, text, _ in _iter_sources(root)})


def static_lock_graph(root: Path) -> dict:
    """The NNS202 acquisition-order graph as JSON-able data: nodes,
    edges (with the acquisition site), and the lock creation-site map
    the runtime witness (``obs/lockgraph.py``) uses to translate its
    observed ``file:line`` lock identities into these symbolic names."""
    models: List[_FileModel] = []
    for rel, text, _ in _iter_sources(root):
        m = _analyze_file(rel, text)
        if m is not None:
            models.append(m)
    for m in models:
        _propagate_acquires(m)
    nodes: Set[str] = set()
    edges: List[dict] = []
    sites: Dict[str, str] = {}
    seen: Set[Tuple[str, str]] = set()
    for m in models:
        for (a, b), site in sorted(m.edges.items(), key=lambda kv: kv[1]):
            da, db = lock_display(a), lock_display(b)
            nodes.add(da)
            nodes.add(db)
            if (da, db) not in seen:
                seen.add((da, db))
                edges.append({"from": da, "to": db, "site": site})
        for site, lock in m.lock_sites.items():
            sites[site] = lock_display(lock)
            nodes.add(lock_display(lock))
    return {"version": 1, "nodes": sorted(nodes),
            "edges": sorted(edges, key=lambda e: (e["from"], e["to"])),
            "sites": sites}
