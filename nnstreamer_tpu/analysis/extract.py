"""Extract pipeline descriptions from Python sources and markdown docs.

The CI lint job verifies every launch description the repo ships — in
``examples/*.py`` and in the fenced snippets of the docs — without
executing any of it. Two extractors:

- Python: AST-walk for ``parse_launch(...)`` calls. A plain string
  literal is taken verbatim; an f-string is taken with each interpolated
  ``{expr}`` replaced by ``"0"`` (ports, counts and paths don't affect
  graph shape, which is all the verifier checks).
- Markdown: fenced ````python`` blocks go through the Python extractor;
  fenced ````bash`` blocks are scanned for ``nns-launch "<desc>"``
  invocations.

Snippets containing a literal ``...`` are placeholders, not runnable
descriptions, and are skipped.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, NamedTuple


class Snippet(NamedTuple):
    """One extracted description plus where it came from."""

    description: str
    source: str     # file path
    line: int       # 1-based line of the description in that file


_FENCE_RE = re.compile(r"^```(\w*)\s*$")
_NNS_LAUNCH_RE = re.compile(
    r"""nns-launch\s+(?:--?[\w-]+(?:[= ][\w./:-]+)?\s+)*["']([^"']+)["']""")


def _fstring_text(node: ast.JoinedStr) -> str:
    """Flatten an f-string, substituting "0" for every interpolation."""
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("0")
    return "".join(parts)


def extract_from_python(text: str, source: str,
                        line_offset: int = 0) -> List[Snippet]:
    """Descriptions passed to ``parse_launch`` in a Python source."""
    out: List[Snippet] = []
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else \
            func.id if isinstance(func, ast.Name) else ""
        if name != "parse_launch" or not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            desc = arg.value
        elif isinstance(arg, ast.JoinedStr):
            desc = _fstring_text(arg)
        else:
            continue
        if "..." in desc:
            continue
        out.append(Snippet(desc, source, arg.lineno + line_offset))
    return out


def extract_from_markdown(text: str, source: str) -> List[Snippet]:
    """Descriptions in fenced code blocks of a markdown document."""
    out: List[Snippet] = []
    lang = None
    block: List[str] = []
    block_start = 0
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _FENCE_RE.match(line.strip())
        if m and lang is None:
            lang = m.group(1).lower()
            block = []
            block_start = lineno
            continue
        if line.strip() == "```" and lang is not None:
            body = "\n".join(block)
            if lang in ("python", "py"):
                out.extend(extract_from_python(body, source,
                                               line_offset=block_start))
            elif lang in ("bash", "sh", "shell", "console", ""):
                for i, bline in enumerate(block):
                    for m2 in _NNS_LAUNCH_RE.finditer(bline):
                        desc = m2.group(1)
                        if "..." not in desc:
                            out.append(Snippet(desc, source,
                                               block_start + 1 + i))
            lang = None
            continue
        if lang is not None:
            block.append(line)
    return out


def extract_from_file(path: Path) -> List[Snippet]:
    """Dispatch on file type; unknown extensions yield nothing."""
    text = path.read_text(encoding="utf-8")
    if path.suffix == ".py":
        return extract_from_python(text, str(path))
    if path.suffix in (".md", ".rst"):
        return extract_from_markdown(text, str(path))
    return []
