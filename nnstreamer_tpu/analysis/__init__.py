"""Static analysis for nnstreamer_tpu: ``nns-lint``.

Two halves sharing one diagnostics model:

- the **pipeline verifier** (:func:`verify_description`,
  :func:`verify_pipeline`) statically checks nns-launch descriptions —
  graph shape, caps/dtype/shape propagation, policy conflicts — without
  constructing any runtime state (codes ``NNS0xx``);
- the **project AST lint** (:func:`lint_tree`) enforces codebase
  invariants like monotonic-clock usage and no blocking calls under
  locks (codes ``NNS1xx``);
- the **whole-program concurrency analysis**
  (:func:`lint_concurrency`) infers lock-guarded attributes, builds the
  project-wide lock-ordering graph (:func:`static_lock_graph` — the
  graph the runtime witness ``obs/lockgraph.py`` cross-checks), and
  flags check-then-act races and foreign calls under lock (codes
  ``NNS2xx``).

See ``docs/linting.md`` for the full diagnostic-code table, the JSON
output schema, and the pragma syntax.
"""

from nnstreamer_tpu.analysis.astlint import (     # noqa: F401
    lint_file,
    lint_source,
    lint_tree,
)
from nnstreamer_tpu.analysis.concurrency import (  # noqa: F401
    lint_concurrency,
    lint_concurrency_source,
    lint_concurrency_sources,
    static_lock_graph,
)
from nnstreamer_tpu.analysis.diagnostics import (  # noqa: F401
    CODE_TABLE,
    ERROR,
    INFO,
    WARNING,
    Diagnostic,
    Location,
    has_errors,
    render_json,
    render_text,
    sort_diagnostics,
    summarize,
)
from nnstreamer_tpu.analysis.verify import (       # noqa: F401
    verify_description,
    verify_pipeline,
)

__all__ = [
    "CODE_TABLE", "Diagnostic", "Location",
    "ERROR", "WARNING", "INFO",
    "has_errors", "render_json", "render_text", "sort_diagnostics",
    "summarize",
    "verify_description", "verify_pipeline",
    "lint_file", "lint_source", "lint_tree",
    "lint_concurrency", "lint_concurrency_source",
    "lint_concurrency_sources", "static_lock_graph",
]
