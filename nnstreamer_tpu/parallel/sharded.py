"""Sharding rules + sharded train/infer step builders.

This is where the scaling-book recipe is applied to the transformer: name
the mesh axes (dp/tp/sp/ep), give every param a PartitionSpec, annotate the
data, jit — XLA inserts all-gathers/reduce-scatters/psums over ICI. Ring
attention (manual ppermute schedule) is spliced in with ``shard_map`` when
the mesh has an ``sp`` axis; everything around it stays GSPMD.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    build_forward,
    init_params,
)
from nnstreamer_tpu.parallel.ring import ring_attention


def transformer_param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    """PartitionSpec per param name. tp shards heads / ff hidden; ep shards
    experts; everything else is replicated (layer axis L is never sharded
    — it is scanned)."""
    specs = {
        "embed": P(None, "tp"),
        "ln1": P(None, None),
        "qkv": P(None, None, None, "tp", None),
        "proj": P(None, "tp", None, None),
        "ln2": P(None, None),
        "ln_f": P(None),
    }
    if cfg.num_experts:
        specs["router"] = P(None, None, "ep")
        specs["w_in"] = P(None, "ep", None, "tp")
        specs["w_out"] = P(None, "ep", "tp", None)
    else:
        specs["w_in"] = P(None, None, "tp")
        specs["w_out"] = P(None, "tp", None)
    return specs


def _mesh_axis(mesh: Mesh, name: str) -> bool:
    return name in mesh.axis_names and mesh.shape[name] > 1


def make_sharded_forward(cfg: TransformerConfig, mesh: Mesh) -> Callable:
    """Forward with ring attention over ``sp`` when present (shard_map
    island inside the GSPMD program)."""
    if _mesh_axis(mesh, "sp"):
        from jax import shard_map

        ring = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P("dp", "sp", "tp", None),) * 3,
            out_specs=P("dp", "sp", "tp", None),
            
        )
        return build_forward(cfg, attention_fn=ring)
    return build_forward(cfg)


def lm_loss(apply_fn: Callable, params, tokens) -> jax.Array:
    """Next-token cross-entropy (fp32 logits)."""
    logits = apply_fn(params, tokens)[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_train_step(cfg: TransformerConfig, mesh: Mesh,
                    learning_rate: float = 1e-3) -> Callable:
    """One SGD step, fully sharded: params per ``transformer_param_specs``,
    batch over dp, sequence over sp. Returns
    train_step(params, tokens) -> (params, loss)."""
    apply_fn = make_sharded_forward(cfg, mesh)
    specs = transformer_param_specs(cfg)
    param_sh = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    data_sh = NamedSharding(
        mesh, P("dp", "sp" if _mesh_axis(mesh, "sp") else None)
    )

    def step(params, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(apply_fn, p, tokens)
        )(params)
        params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
        return params, loss

    return jax.jit(
        step,
        in_shardings=(param_sh, data_sh),
        out_shardings=(param_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def shard_params(params, mesh: Mesh, cfg: TransformerConfig,
                 pipelined: bool = False):
    """Rule-sharded param placement, routed through the serving plane's
    :func:`~nnstreamer_tpu.parallel.serve.place_params` so the per-shard
    HBM registers with the budget accountant (``nns_mem_used_bytes``)
    whenever one is active — multi-chip weights are no longer invisible
    to the memory plane."""
    from nnstreamer_tpu.parallel import serve as _serve

    if pipelined:
        from nnstreamer_tpu.parallel.pipeline import pipeline_param_specs

        specs = pipeline_param_specs(cfg)
    else:
        specs = transformer_param_specs(cfg)
    return _serve.place_params(params, mesh, specs,
                               label="sharded:transformer")


def make_pp_train_step(cfg: TransformerConfig, mesh: Mesh,
                       num_microbatches: int = 4,
                       learning_rate: float = 1e-3) -> Callable:
    """One SGD step with the block stack **pipeline-parallel** over mesh
    axis ``pp`` (microbatched GPipe schedule, parallel.pipeline), composed
    in the same jitted program with tp (Megatron shardings), ep (expert
    axis), sp (ring attention inside the pipelined region) and dp (batch).

    ``tokens`` are ``[num_microbatches, mb_batch, seq]`` int32; the
    microbatch axis is the pipeline's time axis, ``mb_batch`` shards over
    dp, ``seq`` over sp. Returns step(params, tokens) -> (params, loss).
    """
    from nnstreamer_tpu.parallel.pipeline import (
        build_pipelined_forward,
        pipeline_param_specs,
    )

    apply_fn = build_pipelined_forward(cfg, mesh, num_microbatches)
    specs = pipeline_param_specs(cfg)
    param_sh = {k: NamedSharding(mesh, s) for k, s in specs.items()}
    data_sh = NamedSharding(
        mesh, P(None, "dp", "sp" if _mesh_axis(mesh, "sp") else None))

    def loss_fn(params, tokens):
        logits = apply_fn(params, tokens)[:, :, :-1]
        targets = tokens[:, :, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    def step(params, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
        return params, loss

    return jax.jit(
        step,
        in_shardings=(param_sh, data_sh),
        out_shardings=(param_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
