"""Parallel execution: device meshes, sharded invokes, sequence/context
parallelism, and collectives.

The reference's parallelism inventory (SURVEY §2.4) is dataflow-level:
stage pipelining, tee/mux fan-out, aggregator batching, query offload,
repo recurrence. Those all exist here as elements. This package adds what
the TPU makes possible *beyond* the reference — model-level SPMD:

- ``mesh``      — mesh construction + named shardings (dp/tp/sp/ep axes);
- ``ring``      — ring attention (sequence/context parallelism) via
  ``shard_map`` + ``lax.ppermute`` over the ICI ring;
- ``sharded``   — sharding rules for model params + the sharded train/
  infer step builders used by the transformer and ``dryrun_multichip``.

All of it is pure jax.sharding/GSPMD: we annotate, XLA inserts the
collectives (psum/all-gather/reduce-scatter) over ICI.
"""

from nnstreamer_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    batch_sharding,
    BatchSharding,
)
from nnstreamer_tpu.parallel.ring import ring_attention  # noqa: F401
from nnstreamer_tpu.parallel import multihost  # noqa: F401
