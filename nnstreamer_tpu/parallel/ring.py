"""Ring attention — sequence/context parallelism over the ICI ring.

Long-context capability the reference lacks entirely (SURVEY §5
"long-context: absent"): the sequence axis is sharded over mesh axis
``sp``; each device holds a Q/K/V shard and K/V blocks rotate around the
ring with ``lax.ppermute`` while every device accumulates its Q-block's
attention online (flash-attention-style running max/sum renormalization,
so the full sequence never materializes on one chip). Compute on the
current block overlaps the ppermute of the next — XLA schedules the
collective-permute concurrently with the matmuls.

Causal masking uses block indices: device i attends to block j fully when
j < i, diagonally when j == i, not at all when j > i — the standard ring
schedule.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One online-softmax accumulation step (flash-style, numerically
    stable): returns updated (m, l, o)."""
    s = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)                      # [..., h, q]
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    l_corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * l_corr + jnp.sum(p, axis=-1)
    o_corr = l_corr[..., None]
    o_new = o_prev * o_corr + jnp.einsum("...hqk,...khd->...qhd",
                                         p, v).swapaxes(-3, -2)
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Must be called inside ``shard_map`` (or pjit with explicit axis
    context). Shapes per device: q/k/v [batch, seq_shard, heads, head_dim].
    Returns [batch, seq_shard, heads, head_dim].
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = q.shape[-1] ** -0.5
    b, sq, h, d = q.shape

    # derive accumulators from q so they inherit every varying manual axis
    # (dp/tp/sp...) — scan requires carry-in/out VMA types to match
    zq = q[..., 0].swapaxes(1, 2).astype(jnp.float32) * 0.0  # [b,h,sq]
    m0 = zq - jnp.inf
    l0 = zq
    o0 = q.swapaxes(1, 2).astype(jnp.float32) * 0.0          # [b,h,sq,d]

    qf = q.astype(jnp.float32)

    def body(carry, step):
        m, l, o, kb, vb = carry
        src_idx = (my_idx - step) % axis_size  # block kb originated here
        if causal:
            # full block if src < mine; diagonal if equal; skip if greater
            sk = kb.shape[1]
            qi = jnp.arange(sq)[:, None]
            ki = jnp.arange(sk)[None, :]
            diag = jnp.where(qi >= ki, 0.0, -jnp.inf)
            full = jnp.zeros((sq, sk))
            none = jnp.full((sq, sk), -jnp.inf)
            bias = jnp.where(
                src_idx < my_idx, full,
                jnp.where(src_idx == my_idx, diag, none),
            )
            bias = bias[None, None, :, :]
        else:
            bias = None
        m2, l2, o2 = _block_attend(
            qf, kb.astype(jnp.float32), vb.astype(jnp.float32),
            bias, m, l, o, scale,
        )
        # rotate K/V to the next device on the ring; overlaps next matmul
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        kb2 = lax.ppermute(kb, axis_name, perm)
        vb2 = lax.ppermute(vb, axis_name, perm)
        return (m2, l2, o2, kb2, vb2), None

    # o accumulates as [b, h, sq, d] internally
    (m, l, o, _, _), _ = lax.scan(
        body, (m0, l0, o0, k, v), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]     # [b, h, sq, d]
    return out.swapaxes(1, 2).astype(q.dtype)      # [b, sq, h, d]


# canonical single-device reference lives with the flash kernel; re-export
# for the unsharded path and existing importers
from nnstreamer_tpu.ops.flash_attention import attention_reference  # noqa: E402,F401
