"""Device-mesh construction and named shardings.

The scaling recipe (jax-ml.github.io/scaling-book): pick a mesh whose axes
name the parallelism kinds, annotate array shardings, and let XLA lower
collectives onto ICI. The mesh axes used throughout this framework:

- ``dp`` — data/batch parallelism (mux-batched frames split over chips);
- ``tp`` — tensor parallelism (attention heads / mlp hidden sharded);
- ``sp`` — sequence/context parallelism (ring attention over tokens);
- ``ep`` — expert parallelism (MoE experts, one group per chip set).

Helpers here are deliberately small: the mesh is global state the way
jax treats it, and filter backends only need "shard my batch over dp"
(:class:`BatchSharding`) or a full rule-based param sharding
(``parallel.sharded``).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(axes: Sequence[Tuple[str, int]], devices=None):
    """Build a Mesh from (name, size) pairs; size -1 means "the rest".

    make_mesh([("dp", -1), ("tp", 2)]) on 8 devices → 4×2 mesh.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    sizes = [s for _, s in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may have size -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if total % known:
            raise ValueError(f"{total} devices not divisible by {known}")
        sizes[sizes.index(-1)] = total // known
    need = math.prod(sizes)
    if need > total:
        raise ValueError(f"mesh {sizes} needs {need} devices, have {total}")
    arr = np.asarray(devices[:need]).reshape(sizes)  # subset is fine
    return Mesh(arr, axis_names=[n for n, _ in axes])


class BatchSharding:
    """Shard the leading (batch) dim of filter I/O over a 1-D mesh axis —
    the jax backend's ``custom=sharding:<axis>`` option."""

    def __init__(self, axis: str = "dp", mesh=None):
        self.axis = axis
        self.mesh = mesh if mesh is not None else make_mesh([(axis, -1)])

    def batched(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))


def batch_sharding(axis: str = "dp", mesh=None) -> BatchSharding:
    return BatchSharding(axis=axis, mesh=mesh)
