"""Multi-host (multi-process) SPMD support.

The reference scales across machines with hand-rolled CPU transports
(tensor_query TCP, MQTT, gRPC — SURVEY §2.3); tensors always transit host
memory. The TPU-native equivalent keeps *control* on DCN but moves tensor
traffic onto XLA collectives: every host runs the same program, jax's
distributed runtime forms the global device mesh, and pjit/shard_map
insert ICI/DCN collectives. This module is the thin bootstrap around
that — the moral peer of the reference's query-server handshake, not of
its data path.

Usage (same script on every host)::

    from nnstreamer_tpu.parallel import multihost

    multihost.initialize()            # env-driven; no-op single-process
    mesh = multihost.global_mesh([("dp", -1)])
    ...                               # pjit/shard_map as usual

Env (mirroring jax.distributed's own knobs):
  NNSTPU_COORDINATOR  host:port of process 0 (or JAX_COORDINATOR_ADDRESS)
  NNSTPU_NUM_PROCESSES / NNSTPU_PROCESS_ID
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

from nnstreamer_tpu.log import get_logger

log = get_logger("parallel.multihost")

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the jax distributed runtime. Explicit args beat env vars; with
    neither (or a single process) this is a no-op returning False —
    single-host pipelines never pay a coordinator round trip."""
    global _initialized
    if _initialized:
        return True
    coordinator_address = (coordinator_address
                           or os.environ.get("NNSTPU_COORDINATOR")
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None:
        env = os.environ.get("NNSTPU_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("NNSTPU_PROCESS_ID")
        process_id = int(env) if env else None
    if not coordinator_address or not num_processes or num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    log.info("joined distributed runtime: process %d/%d via %s",
             jax.process_index(), jax.process_count(), coordinator_address)
    return True


def process_info() -> Tuple[int, int]:
    """(process_index, process_count) — (0, 1) when single-process."""
    import jax

    return jax.process_index(), jax.process_count()


def global_mesh(axes: Sequence[Tuple[str, int]]):
    """A mesh over ALL devices across every host (``jax.devices()`` is
    global after :func:`initialize`). An axis size of -1 absorbs the
    remaining device count, so the same spec works on any slice size."""
    import jax

    from nnstreamer_tpu.parallel.mesh import make_mesh

    total = len(jax.devices())
    fixed = 1
    wildcard = None
    resolved = []
    for name, size in axes:
        if size == -1:
            if wildcard is not None:
                raise ValueError("only one axis may be -1")
            wildcard = name
            resolved.append((name, -1))
        else:
            fixed *= size
            resolved.append((name, size))
    if wildcard is not None:
        if total % fixed:
            raise ValueError(
                f"{total} devices not divisible by fixed axes ({fixed})")
        resolved = [(n, total // fixed if s == -1 else s)
                    for n, s in resolved]
    return make_mesh(resolved)


def local_batch_slice(global_batch: int) -> slice:
    """Which rows of a global batch THIS host feeds (data loading is
    per-host in SPMD: every process reads only its shard)."""
    idx, count = process_info()
    if global_batch % count:
        raise ValueError(
            f"global batch {global_batch} not divisible by {count} hosts")
    per = global_batch // count
    return slice(idx * per, (idx + 1) * per)


def host_local_to_global(arrays, mesh, pspec):
    """Assemble per-host shards into one global ``jax.Array``
    (``jax.make_array_from_process_local_data``) — feed pipelines on each
    host, train globally."""
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, pspec), arrays)
