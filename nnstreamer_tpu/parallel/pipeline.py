"""Pipeline parallelism — GPipe-style microbatch pipelining over mesh
axis ``pp``, TPU-idiomatic: one SPMD program, stages rotate activations
around the ICI ring with ``lax.ppermute``.

New capability beyond the reference: its "pipeline parallelism" is
dataflow threading of stream elements (SURVEY §2.4.1 — GStreamer queue
decoupling, throughput = slowest stage). Here the model itself is cut into
stages: the stacked layer axis L is sharded over ``pp`` (each stage holds
L/pp contiguous blocks), a batch is split into microbatches, and the
classic pipeline schedule runs for ``num_microbatches + pp - 1`` steps. At
each step every stage applies its local blocks to the microbatch it
currently holds, then ppermutes the activation to the next stage — so the
ICI transfer of step t overlaps the matmuls of step t+1 under XLA's
scheduler, and the bubble fraction is (pp-1)/(num_mb+pp-1).

Composes with the other four axes in ONE jitted program via
partial-manual ``shard_map``: the region is manual over {pp, sp} (ring
attention needs manual sp), while tp/ep/dp stay auto — GSPMD keeps
inserting the Megatron-style all-reduces for tp and the expert all-to-all
for ep inside each stage.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    make_layer_body,
)
from nnstreamer_tpu.parallel.ring import ring_attention


def pipelined_block_forward(cfg: TransformerConfig, mesh: Mesh) -> Callable:
    """Returns ``blocks(stage_params, x, positions) -> y`` where

    - ``x``/``y``: activations ``[num_mb, mb_batch, seq, d_model]``,
    - ``positions``: ``[num_mb, mb_batch, seq]`` global rotary positions,
    - ``stage_params``: stacked layer params whose leading L axis is
      sharded over ``pp`` (each stage sees L/pp locally).

    The returned function is already wrapped in shard_map (manual over
    pp and sp) and must be called under the given mesh (inside jit).
    """
    has_sp = "sp" in mesh.axis_names
    manual = {"pp"} | ({"sp"} if has_sp else set())
    attn = (functools.partial(ring_attention, axis_name="sp", causal=True)
            if has_sp else None)
    layer_body = make_layer_body(cfg, attn)

    def stage_fn(stage_params, x, positions):
        """Apply this stage's local blocks (scan over L/pp layers)."""
        (x, _), _ = lax.scan(layer_body, (x, positions), stage_params)
        return x

    def pipeline(stage_params, x, positions):
        n_stages = lax.psum(1, "pp")
        stage = lax.axis_index("pp")
        num_mb = x.shape[0]
        pos0 = positions[0]          # identical for every microbatch
        state = jnp.zeros_like(x[0])
        out = jnp.zeros_like(x)

        def step(carry, t):
            state, out = carry
            # stage 0 ingests microbatch t (clamped; t >= num_mb steps are
            # drain-only), others take what the ring delivered last step
            inp = lax.dynamic_index_in_dim(
                x, jnp.minimum(t, num_mb - 1), 0, keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            cur = stage_fn(stage_params, cur, pos0)
            # the microbatch finishing at the last stage this step
            oidx = t - (n_stages - 1)
            valid = jnp.logical_and(stage == n_stages - 1, oidx >= 0)
            slot = jnp.maximum(oidx, 0)
            prev = lax.dynamic_index_in_dim(out, slot, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, cur, prev), slot, 0)
            # rotate activations one stage forward around the ICI ring
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            state = lax.ppermute(cur, "pp", perm)
            return (state, out), None

        (state, out), _ = lax.scan(
            step, (state, out), jnp.arange(num_mb + n_stages - 1))
        # results live on the last stage only; psum == broadcast since all
        # other stages contribute zeros
        return lax.psum(jnp.where(stage == n_stages - 1, out,
                                  jnp.zeros_like(out)), "pp")

    seq_spec = "sp" if has_sp else None
    return jax.shard_map(
        pipeline,
        mesh=mesh,
        axis_names=frozenset(manual),
        in_specs=(
            jax.tree.map(lambda _: P("pp"), _stage_param_tree(cfg)),
            P(None, None, seq_spec, None),
            P(None, None, seq_spec),
        ),
        out_specs=P(None, None, seq_spec, None),
        check_vma=False,
    )


def _stage_param_tree(cfg: TransformerConfig) -> Dict[str, int]:
    """Skeleton pytree matching the stacked layer params (values unused)."""
    keys = ["ln1", "qkv", "proj", "ln2"]
    keys += (["router", "w_in", "w_out"] if cfg.num_experts
             else ["w_in", "w_out"])
    return {k: 0 for k in keys}


def pipeline_param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    """PartitionSpecs for the pipelined model: L axis over ``pp``, tp/ep
    exactly as the GSPMD path (parallel.sharded.transformer_param_specs)."""
    specs = {
        "embed": P(None, "tp"),
        "ln1": P("pp", None),
        "qkv": P("pp", None, None, "tp", None),
        "proj": P("pp", "tp", None, None),
        "ln2": P("pp", None),
        "ln_f": P(None),
    }
    if cfg.num_experts:
        specs["router"] = P("pp", None, "ep")
        specs["w_in"] = P("pp", "ep", None, "tp")
        specs["w_out"] = P("pp", "ep", "tp", None)
    else:
        specs["w_in"] = P("pp", None, "tp")
        specs["w_out"] = P("pp", "tp", None)
    return specs


def build_pipelined_forward(cfg: TransformerConfig, mesh: Mesh,
                            num_microbatches: int) -> Callable:
    """apply_fn(params, tokens[int32 num_mb, mb, s]) -> logits
    [num_mb, mb, s, vocab]. Embedding/unembedding run replicated across pp
    under plain GSPMD; only the block stack is pipelined."""
    dtype = cfg.dtype
    blocks = pipelined_block_forward(cfg, mesh)

    def apply_fn(params, tokens):
        num_mb, mb, s = tokens.shape
        if num_mb != num_microbatches:
            raise ValueError(
                f"tokens leading dim {num_mb} != num_microbatches "
                f"{num_microbatches} the step was built for")
        positions = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32)[None, None, :], tokens.shape)
        x = params["embed"].astype(dtype)[tokens]   # [num_mb, mb, s, d]
        stage_params = {k: v for k, v in params.items()
                        if k not in ("embed", "ln_f")}
        x = blocks(stage_params, x, positions)
        from nnstreamer_tpu.models.transformer import _rmsnorm

        x = _rmsnorm(x, params["ln_f"])
        return jnp.einsum("mbsd,vd->mbsv", x.astype(jnp.float32),
                          params["embed"])

    return apply_fn
