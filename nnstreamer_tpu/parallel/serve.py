"""The mesh-sharded SERVING plane: mesh specs, plans, and placements.

``parallel/{mesh,sharded,ring}.py`` give training/offline code the full
scaling-book toolbox. This module is the narrow serving-side facade the
pipeline uses: a ``tensor_filter``'s (or fused region's) ``mesh=`` property
names a mesh spec here, and everything that CONSTRUCTS a sharding on its
behalf — batch shardings for frame I/O, replicated/rule-based weight
placements, reshard moves — lives behind these helpers. Lint rule NNS117
enforces exactly that: ``NamedSharding``/``shard_map``/``pjit`` built
outside ``parallel/`` is a finding, so every sharding decision stays
auditable in one package.

Mesh-spec grammar
-----------------
``<axis><size>`` tokens joined with ``x``; axes are the framework's
canonical mesh axes (``dp``/``tp``/``sp``/``ep``/``pp``, see
``parallel.mesh``); size ``-1`` (or ``*``) means "the rest of the
devices". Examples::

    mesh=dp4        # 4-way batch (data) parallel
    mesh=dp8        # the CI multi-device smoke (8 virtual CPU devices)
    mesh=dp2xtp2    # 2-way batch over a 2x2 mesh, weights replicated
                    # over tp unless the backend supplies param specs
    mesh=dp-1       # batch-shard over every visible device

Serving semantics: the LEADING (batch) dimension of every frame tensor
shards over ``dp``; weights replicate over the whole mesh (one full copy
per chip — which is exactly what the per-shard residency units account).
Axes other than ``dp`` exist so GSPMD programs with real param specs
(``parallel.sharded``) can ride the same mesh.

Matched-sharding contract
-------------------------
Two sharded regions hand DeviceBuffers to each other through
device-passthrough elements (queues). The hand-off moves ZERO bytes iff
the producer's out-sharding equals the consumer's in-sharding —
``pipeline/fuse.py`` verifies that at PLAN time (a mismatch is a hard
:class:`MeshShardingError` before any frame flows, per SNIPPETS [1]'s
pjit-to-pjit matched-sharding rule). Any RUNTIME placement that does move
device bytes between shardings goes through :func:`place_batch`, which
counts them in ``nns_reshard_bytes_total`` — the counter that must stay 0
across matched boundaries.

Kill switch: ``NNSTPU_MESH=0`` (or no ``mesh=`` property anywhere) keeps
:func:`mesh_enabled` False; every caller then behaves byte-identically to
the single-device path — the ``NNSTPU_FAULTS``/``NNSTPU_TRACE``/
``NNSTPU_HBM_BUDGET`` kill-switch discipline.
"""

from __future__ import annotations

import itertools
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.parallel.mesh import make_mesh
from nnstreamer_tpu.tensors import memory as _memory

log = get_logger("mesh-serve")

_ENV = "NNSTPU_MESH"

#: canonical mesh axis names, in the order parallel/mesh.py documents them
MESH_AXES = ("dp", "tp", "sp", "ep", "pp")

#: buffer meta key: the canonical mesh spec whose plan produced the
#: buffer's (sharded) device tensors — stamped by sharded fused regions
MESH_SPEC_META = "mesh-spec"


class MeshShardingError(RuntimeError):
    """A sharding contract violation caught at PLAN time: mismatched
    in/out shardings across a device-passthrough boundary, mixed mesh
    specs inside one fused region, or an unparseable spec. Deliberately
    NOT a FlowError — fusion fallback must not swallow it."""


def mesh_enabled() -> bool:
    """The ``NNSTPU_MESH`` kill switch (default ON — the mesh only
    engages where a ``mesh=`` property asks for it anyway)."""
    return os.environ.get(_ENV, "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def parse_mesh_spec(spec: str) -> List[Tuple[str, int]]:
    """``"dp2xtp2"`` → ``[("dp", 2), ("tp", 2)]`` (see module docstring
    for the grammar). Raises :class:`MeshShardingError` on malformed
    specs so a typo is a plan-time error, not a silent single-device
    fallback."""
    text = str(spec or "").strip().lower()
    if not text:
        raise MeshShardingError("empty mesh spec")
    axes: List[Tuple[str, int]] = []
    seen = set()
    for token in text.split("x"):
        token = token.strip()
        name = None
        for cand in MESH_AXES:
            if token.startswith(cand):
                name = cand
                break
        if name is None:
            raise MeshShardingError(
                f"mesh spec {spec!r}: token {token!r} does not start with "
                f"one of the mesh axes {'/'.join(MESH_AXES)}")
        if name in seen:
            raise MeshShardingError(
                f"mesh spec {spec!r}: duplicate axis {name!r}")
        seen.add(name)
        size_text = token[len(name):]
        if size_text in ("*", ""):
            size = -1
        else:
            try:
                size = int(size_text)
            except ValueError:
                raise MeshShardingError(
                    f"mesh spec {spec!r}: bad size {size_text!r} for axis "
                    f"{name!r}") from None
        if size == 0 or size < -1:
            raise MeshShardingError(
                f"mesh spec {spec!r}: axis {name!r} size must be positive "
                f"or -1, got {size}")
        axes.append((name, size))
    return axes


class MeshPlan:
    """One parsed-and-built mesh spec: the Mesh plus the (cached)
    NamedShardings serving needs. Implements the same ``batched()`` /
    ``replicated()`` / ``num_devices`` surface as
    ``parallel.mesh.BatchSharding`` so filter backends treat either as
    "the sharding"."""

    def __init__(self, spec: str):
        self.spec = canonical_spec(spec)
        self.axes = parse_mesh_spec(spec)
        self.mesh = make_mesh(self.axes)
        self._batched = None
        self._replicated = None

    @property
    def shard_count(self) -> int:
        """Total devices in the mesh (= the dp fan-out times any inner
        axes; what ``nns_shard_count`` reports)."""
        return int(self.mesh.size)

    @property
    def num_devices(self) -> int:  # BatchSharding-compatible alias
        return self.shard_count

    @property
    def batch_axis(self) -> Optional[str]:
        return "dp" if any(n == "dp" for n, _ in self.axes) else None

    @property
    def dp_size(self) -> int:
        return int(self.mesh.shape["dp"]) if self.batch_axis else 1

    def sharding_for(self, x):
        """The placement for one frame tensor: :meth:`batched` when its
        leading dim splits evenly over ``dp``, else :meth:`replicated`
        — a ragged or sub-mesh batch (e.g. a flush tail, or a
        single-frame pipeline someone slapped ``mesh=dp8`` on) runs
        replicated instead of erroring. The mesh must never make a
        legal single-device pipeline illegal; it only speeds up the
        batches that actually split."""
        shape = getattr(x, "shape", None)
        if self.batch_axis and shape and len(shape) >= 1 \
                and shape[0] % self.dp_size == 0:
            return self.batched()
        return self.replicated()

    def batched(self):
        """Leading-dim (batch) sharding over ``dp``; replicated when the
        mesh has no dp axis (still a valid — if pointless — plan)."""
        if self._batched is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._batched = NamedSharding(
                self.mesh, P(self.batch_axis) if self.batch_axis else P())
        return self._batched

    def replicated(self):
        if self._replicated is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._replicated = NamedSharding(self.mesh, P())
        return self._replicated

    def __repr__(self):
        return f"<MeshPlan {self.spec} {dict(self.mesh.shape)}>"


def canonical_spec(spec: str) -> str:
    """Normalized spec text (lowercased, stripped) — the comparison key
    for the matched-sharding contract and the plan cache."""
    return str(spec or "").strip().lower()


#: plan cache: building a Mesh enumerates devices; one plan per spec per
#: process (jax's device set is process-global, so this never goes stale)
_plans: Dict[str, MeshPlan] = {}
_plans_lock = threading.Lock()


def get_mesh_plan(spec: str) -> MeshPlan:
    key = canonical_spec(spec)
    with _plans_lock:
        plan = _plans.get(key)
    if plan is not None:
        return plan
    # build OUTSIDE the lock (mesh construction enumerates devices);
    # a racing builder loses to setdefault and its plan is dropped —
    # plans for one spec are interchangeable, so that is harmless
    built = MeshPlan(key)
    with _plans_lock:
        plan = _plans.setdefault(key, built)
    if plan is built:
        # the reshard counter exports (at 0) as soon as any mesh plan
        # exists: the matched-boundary CI gate asserts on it
        _reshard_counter()
        log.info("mesh plan %s: %d devices %s", key, plan.shard_count,
                 dict(plan.mesh.shape))
    return plan


# --------------------------------------------------------------------------
# reshard accounting — nns_reshard_bytes_total
# --------------------------------------------------------------------------
_m_reshard = None


def _reshard_counter():
    global _m_reshard
    if _m_reshard is None:
        from nnstreamer_tpu.obs import get_registry

        _m_reshard = get_registry().counter(
            "nns_reshard_bytes_total",
            "Device bytes moved to FIX a sharding mismatch at runtime "
            "(device array re-placed onto a different sharding). Stays 0 "
            "across matched fused-region boundaries — the zero-copy "
            "hand-off contract.")
    return _m_reshard


def reshard_bytes_total() -> int:
    """Current counter value (0 when no mesh plan ever resharded)."""
    return int(_m_reshard.value) if _m_reshard is not None else 0


def shardings_match(a, b) -> bool:
    """Whether two shardings place data identically (the zero-copy
    hand-off test). None compares unequal to everything."""
    if a is None or b is None:
        return False
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 — foreign sharding types: not equal
        return False


def place_batch(x, plan: MeshPlan, shard_span: Optional[list] = None):
    """Place one frame tensor for a sharded invoke.

    - already a device array with the plan's batch sharding → returned
      as-is, ZERO bytes moved (the matched hand-off fast path);
    - a device array with any OTHER sharding → re-placed, and the moved
      bytes count into ``nns_reshard_bytes_total``;
    - a host array → plain H2D upload (counted upstream at
      to_device/upload_many like every other ingest transfer, NOT a
      reshard).

    ``shard_span``, when given, collects ``(kind, nbytes)`` tuples so the
    caller can emit one flight-recorder ``shard`` span per invoke."""
    import jax

    tgt = plan.sharding_for(x)
    if isinstance(x, jax.Array):
        if shardings_match(getattr(x, "sharding", None), tgt):
            return x
        moved = int(getattr(x, "nbytes", 0))
        _reshard_counter().inc(moved)
        if shard_span is not None:
            shard_span.append(("reshard", moved))
        return jax.device_put(x, tgt)  # nns-lint: disable=NNS113 -- counted above in nns_reshard_bytes_total; the frame's H2D bytes were tracked at its original upload
    if shard_span is not None:
        shard_span.append(("scatter", int(getattr(x, "nbytes", 0))))
    return jax.device_put(x, tgt)  # nns-lint: disable=NNS113 -- transient invoke input scatter; the frame's bytes are tracked upstream at to_device/upload_many


# --------------------------------------------------------------------------
# weight placement + per-shard accounting
# --------------------------------------------------------------------------
_place_ids = itertools.count()


def _per_device_nbytes(leaves) -> Dict[Any, int]:
    """Actual bytes each mesh device holds for ``leaves`` (from the
    arrays' addressable shards — exact for replicated AND rule-sharded
    placements)."""
    per: Dict[Any, int] = {}
    for leaf in leaves:
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        for sh in shards:
            per[sh.device] = per.get(sh.device, 0) + int(sh.data.nbytes)
    return per


def account_placement(placed: Any, label: str) -> None:
    """Register an externally-held sharded placement's per-device bytes
    with the active HBM accountant as PINNED per-shard residency units
    (satellite of NNS113: the bytes show in ``nns_mem_used_bytes``
    instead of hiding behind a pragma). The units un-register when the
    placed pytree dies — they are accounting, not an eviction target,
    because the caller (a train step, the serving engine) holds the
    arrays and an eviction here could not actually free them."""
    acct = _memory.ACTIVE
    if acct is None:
        return
    import jax

    leaves = [x for x in jax.tree.leaves(placed)
              if hasattr(x, "addressable_shards")]
    if not leaves:
        return
    per = _per_device_nbytes(leaves)
    if not per:
        return
    base = f"place:{next(_place_ids)}:{label}"
    keys = []
    for k, (_dev, nbytes) in enumerate(sorted(
            per.items(), key=lambda kv: str(kv[0]))):
        key = f"{base}:shard{k}"
        acct.residency.adopt(key, nbytes, label=f"{label}#shard{k}")
        keys.append(key)
    try:
        weakref.finalize(leaves[0], _release_placement,
                         weakref.ref(acct), tuple(keys))
    except TypeError:
        # not weakref-able (unexpected for jax arrays): count the
        # placement but release immediately rather than leak forever
        _release_placement(weakref.ref(acct), tuple(keys))


def _release_placement(acct_ref, keys: Tuple[str, ...]) -> None:
    """Module-level finalizer target: retire a dead placement's pinned
    units against the SAME accountant that adopted them."""
    acct = acct_ref()
    if acct is None:
        return
    for key in keys:
        acct.residency.unregister(key)


def place_params(params: Dict[str, Any], mesh, specs: Dict[str, Any],
                 label: str = "params") -> Dict[str, Any]:
    """Rule-sharded param placement WITH accounting: device_put each
    entry per its PartitionSpec and register the per-shard HBM with the
    budget accountant (when active). This is the sanctioned home for
    what used to be raw ``jax.device_put(v, NamedSharding(...))`` sites
    in ``parallel/sharded.py`` and ``serving/engine.py``."""
    import jax
    from jax.sharding import NamedSharding

    placed = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))  # nns-lint: disable=NNS113 -- the per-shard bytes register with the accountant two lines down (account_placement)
        for k, v in params.items()
    }
    account_placement(placed, label)
    return placed


def place_tree(tree: Any, mesh, spec_of: Callable[[Any], Any],
               label: str = "tree", register: bool = False) -> Any:
    """Mesh placement for an arbitrary pytree: ``spec_of(leaf)`` names
    each leaf's PartitionSpec. ``register=True`` additionally accounts
    the per-shard bytes (off by default — e.g. a KV cache is working
    state the engine resizes on its own schedule)."""
    import jax
    from jax.sharding import NamedSharding

    placed = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, spec_of(a))),  # nns-lint: disable=NNS113 -- sharded placement helper; callers opt into accounting via register=True
        tree)
    if register:
        account_placement(placed, label)
    return placed
