"""Broker-based query-server discovery (reference tensor_query_hybrid).

Reference: ``gst/nnstreamer/tensor_query/tensor_query_hybrid.c`` (375 LoC):
servers publish their endpoint under an MQTT topic named after the
``operation`` they serve; clients subscribe, collect the candidate server
list, and fail over through it (tensor_query_hybrid.h:49-116).

Here the broker is ``query.pubsub``; endpoints are JSON
``{"host": ..., "port": ..., "ts": ...}`` retained under
``nns-query/<operation>/<host>:<port>``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query.pubsub import Client

log = get_logger("discovery")

TOPIC_PREFIX = "nns-query/"


class ServerAdvertiser:
    """Server side: publish (retained) this server's endpoint for an
    operation (reference tensor_query_hybrid_publish)."""

    def __init__(self, broker_host: str, broker_port: int, operation: str,
                 host: str, port: int):
        self.client = Client(broker_host, broker_port)
        self.topic = f"{TOPIC_PREFIX}{operation}/{host}:{port}"
        self.endpoint = {"host": host, "port": port, "ts": time.time()}

    def publish(self) -> None:
        self.client.publish(self.topic,
                            json.dumps(self.endpoint).encode(), retain=True)

    def retract(self) -> None:
        self.client.publish(self.topic, b"", retain=True)  # tombstone
        self.client.close()


class ServerDiscovery:
    """Client side: subscribe to an operation's topic and keep the live
    server list (reference tensor_query_hybrid_subscribe /
    _get_server_info)."""

    def __init__(self, broker_host: str, broker_port: int, operation: str):
        self.client = Client(broker_host, broker_port)
        self._servers: Dict[str, Tuple[str, int]] = {}
        self._lock = threading.Lock()
        self._seen = threading.Event()
        self.client.subscribe(f"{TOPIC_PREFIX}{operation}/#", self._on_msg)

    def _on_msg(self, topic: str, body: bytes) -> None:
        key = topic.rsplit("/", 1)[-1]
        with self._lock:
            if not body:
                self._servers.pop(key, None)  # tombstone
            else:
                try:
                    info = json.loads(body.decode())
                    self._servers[key] = (info["host"], int(info["port"]))
                except (ValueError, KeyError) as e:
                    log.warning("bad discovery payload on %s: %s", topic, e)
                    return
                self._seen.set()  # only live endpoints count as "seen"

    def wait_servers(self, timeout: float = 5.0,
                     settle: float = 0.2) -> List[Tuple[str, int]]:
        """Wait up to ``timeout`` for at least one live server, then a
        short ``settle`` window so same-burst retained messages land and
        the failover list is complete — a tombstone alone never satisfies
        the wait."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._seen.wait(timeout=min(0.1, max(0.0, deadline -
                                                    time.monotonic()))):
                break
        with self._lock:
            have = bool(self._servers)
        if have and settle > 0:
            time.sleep(settle)  # collect the rest of the retained burst
        with self._lock:
            return list(self._servers.values())

    def close(self) -> None:
        self.client.close()
