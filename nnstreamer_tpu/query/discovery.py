"""Broker-based query-server discovery (reference tensor_query_hybrid).

Reference: ``gst/nnstreamer/tensor_query/tensor_query_hybrid.c`` (375 LoC):
servers publish their endpoint under an MQTT topic named after the
``operation`` they serve; clients subscribe, collect the candidate server
list, and fail over through it (tensor_query_hybrid.h:49-116).

Endpoints are JSON ``{"host": ..., "port": ..., "ts": ...}`` retained
under ``nns-query/<operation>/<host>:<port>``. The broker transport is
selected by the ``broker_host`` spelling: a plain host speaks the
in-process shim protocol (``query.pubsub``); ``mqtt://host[:port]``
speaks real MQTT 3.1.1 (``query.mqtt.MqttClient``) so discovery works
through any conformant broker and interops with reference query-hybrid
peers (tensor_query_hybrid.c publishes through paho the same way).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query.pubsub import Client

log = get_logger("discovery")

TOPIC_PREFIX = "nns-query/"


def make_broker_client(broker_host: str, broker_port: int):
    """Broker transport factory: ``mqtt`` / ``mqtt://h[:p]`` → real MQTT
    client, anything else is a plain shim-broker host. The mqtt dialect
    is parsed by the shared :func:`~nnstreamer_tpu.query.pubsub.
    parse_broker_spec` (same spelling as the pubsub elements' ``broker``
    property); both transports expose the same publish/subscribe/close
    surface, retain included."""
    spec = str(broker_host or "").strip()
    if spec == "mqtt" or spec.startswith("mqtt://"):
        from nnstreamer_tpu.query.mqtt import MqttClient
        from nnstreamer_tpu.query.pubsub import parse_broker_spec

        _, h, p = parse_broker_spec(spec, "127.0.0.1", int(broker_port))
        return MqttClient(h, p)
    return Client(spec or "127.0.0.1", int(broker_port))


class ServerAdvertiser:
    """Server side: publish (retained) this server's endpoint for an
    operation (reference tensor_query_hybrid_publish).

    With ``refresh_s`` > 0 the ad is re-published on that cadence (meant
    to ride under a client's ``stale_s`` TTL, so a live replica never
    ages out), each refresh carrying a fresh ``ts`` and — when a
    ``load_fn`` is wired — a fresh ``load`` block (queue depth / slack
    headroom from the replica's scheduler) for the shortest-slack
    balancer. ``refresh_s`` 0 keeps the classic publish-once behavior."""

    def __init__(self, broker_host: str, broker_port: int, operation: str,
                 host: str, port: int, metrics_port: Optional[int] = None,
                 load_fn=None, refresh_s: float = 0.0):
        self.client = make_broker_client(broker_host, broker_port)
        self.topic = f"{TOPIC_PREFIX}{operation}/{host}:{port}"
        wall_ts = time.time()  # advertised epoch timestamp, read by peers
        self.endpoint = {"host": host, "port": port, "ts": wall_ts}
        if metrics_port:
            # fleet federation (obs/distributed.py) scrapes replicas that
            # advertise where their /metrics.json lives
            self.endpoint["metrics_port"] = int(metrics_port)
        #: () → load dict for the ad's ``load`` block (or None to omit);
        #: see query/balance.py:parse_ad_load for the field contract
        self.load_fn = load_fn
        self.refresh_s = float(refresh_s or 0.0)
        self._stop = threading.Event()
        self._refresher: Optional[threading.Thread] = None

    def _payload(self) -> bytes:
        ad = dict(self.endpoint)
        wall_ts = time.time()  # refreshed stamp: peers judge staleness
        ad["ts"] = wall_ts
        if self.load_fn is not None:
            try:
                load = self.load_fn()
            except Exception as e:  # noqa: BLE001 — an ad without a load
                # block is still a valid ad; the balancer falls back to
                # RTT-only for this endpoint instead of losing it
                log.warning("advertiser load_fn failed: %s", e)
                load = None
            if load:
                ad["load"] = load
        return json.dumps(ad).encode()

    def publish(self) -> None:
        self.client.publish(self.topic, self._payload(), retain=True)
        if self.refresh_s > 0 and self._refresher is None:
            self._refresher = threading.Thread(
                target=self._refresh_loop, name="discovery-refresh",
                daemon=True)
            self._refresher.start()

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_s):
            try:
                self.client.publish(self.topic, self._payload(),
                                    retain=True)
            except OSError as e:
                log.warning("ad refresh lost broker: %s", e)
                return

    def retract(self) -> None:
        self._stop.set()
        if self._refresher is not None:
            self._refresher.join(timeout=2.0)
            self._refresher = None
        self.client.publish(self.topic, b"", retain=True)  # tombstone
        self.client.close()


class ServerDiscovery:
    """Client side: subscribe to an operation's topic and keep the live
    server list (reference tensor_query_hybrid_subscribe /
    _get_server_info)."""

    def __init__(self, broker_host: str, broker_port: int, operation: str,
                 stale_s: Optional[float] = None):
        #: entries whose advertised ``ts`` is older than this many
        #: seconds are filtered out of ``wait_servers`` results — a
        #: server that died without retracting leaves a retained ad
        #: behind forever otherwise. ``None`` (default) keeps the
        #: classic trust-the-broker behavior.
        self.stale_s = stale_s
        self.client = make_broker_client(broker_host, broker_port)
        #: key → (host, port, advertised epoch ts; 0.0 = no ts in ad)
        self._servers: Dict[str, Tuple[str, int, float]] = {}
        #: key → full advertised payload (extra fields like metrics_port)
        self._meta: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._seen = threading.Event()
        self.client.subscribe(f"{TOPIC_PREFIX}{operation}/#", self._on_msg)

    def _on_msg(self, topic: str, body: bytes) -> None:
        key = topic.rsplit("/", 1)[-1]
        with self._lock:
            if not body:
                self._servers.pop(key, None)  # tombstone
                self._meta.pop(key, None)
            else:
                try:
                    info = json.loads(body.decode())
                    self._servers[key] = (info["host"], int(info["port"]),
                                          float(info.get("ts", 0.0)))
                    self._meta[key] = info
                except (ValueError, KeyError) as e:
                    log.warning("bad discovery payload on %s: %s", topic, e)
                    return
                self._seen.set()  # only live endpoints count as "seen"

    def _live_locked(self) -> List[Tuple[str, int]]:
        if self.stale_s is None:
            return [(h, p) for h, p, _ts in self._servers.values()]
        # deliberately wall-clock: the advertised ts is a peer's epoch
        # stamp, comparable only against our own epoch clock
        wall_now = time.time()
        cutoff = wall_now - self.stale_s
        out = []
        for key, (h, p, ts) in list(self._servers.items()):
            # ts==0.0 = ad without a timestamp (older peer): trusted,
            # staleness can only be judged against an advertised clock
            if ts and ts < cutoff:
                log.info("discovery: dropping stale ad %s (%.1fs old)",
                         key, wall_now - ts)
                self._servers.pop(key)
                self._meta.pop(key, None)
                continue
            out.append((h, p))
        return out

    def wait_servers(self, timeout: float = 5.0,
                     settle: float = 0.2) -> List[Tuple[str, int]]:
        """Wait up to ``timeout`` for at least one live server, then a
        short ``settle`` window so same-burst retained messages land and
        the failover list is complete — a tombstone alone never satisfies
        the wait. Mid-wait retractions are honored: a server that
        advertises and then tombstones before the settle window closes
        is not returned."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._seen.wait(timeout=min(0.1, max(0.0, deadline -
                                                    time.monotonic()))):
                with self._lock:
                    have = bool(self._live_locked())
                if have:
                    break
                self._seen.clear()  # everything seen so far went stale
        with self._lock:
            have = bool(self._servers)
        if have and settle > 0:
            time.sleep(settle)  # collect the rest of the retained burst
        with self._lock:
            return self._live_locked()

    def servers_now(self) -> List[Tuple[str, int]]:
        """Non-blocking live-server snapshot (stale ads evicted) — the
        balancer's per-route refresh, vs ``wait_servers`` which blocks
        for the first ad."""
        with self._lock:
            return self._live_locked()

    def load(self, host: str, port: int) -> Optional[dict]:
        """The raw ``load`` block of this endpoint's latest ad, or None
        when the ad carries none (pre-fleet replica, or the endpoint is
        unknown). Parsing/validation is the balancer's job
        (``query.balance.parse_ad_load``)."""
        with self._lock:
            info = self._meta.get(f"{host}:{port}")
        if not info:
            return None
        load = info.get("load")
        return load if isinstance(load, dict) else None

    def metrics_endpoints(self) -> List[Tuple[str, int]]:
        """``(host, metrics_port)`` for every live server whose ad
        carries a ``metrics_port`` — the fleet-federation scrape list
        (see :class:`~nnstreamer_tpu.obs.distributed.FederatedMetrics`)."""
        with self._lock:
            out = []
            for key in list(self._servers):
                info = self._meta.get(key) or {}
                mp = info.get("metrics_port")
                if mp:
                    out.append((str(info.get("host", "")), int(mp)))
            return out

    def close(self) -> None:
        self.client.close()
