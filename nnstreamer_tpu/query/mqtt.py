"""MQTT 3.1.1 — real protocol framing for the pubsub elements.

Reference: ``gst/mqtt/mqttsink.c`` / ``mqttsrc.c`` speak MQTT through
paho; their payloads prepend the fixed 1024-byte ``GstMQTTMessageHdr``
(``gst/mqtt/mqttcommon.h:49-63``) so any subscriber can reconstruct the
buffer. This module provides the same capability without paho:

- **packet codec** — CONNECT/CONNACK/SUBSCRIBE/SUBACK/PUBLISH(QoS0/
  QoS1, retain)/PUBACK/PING*/DISCONNECT encode+decode per the MQTT
  3.1.1 spec (unit-tested always; any conformant broker understands
  them);
- :class:`MqttClient` — a minimal client (same surface as the in-process
  shim's ``Client``) usable against any broker reachable at
  ``mqtt://host:port``;
- :class:`MqttBroker` — an in-process broker speaking real MQTT, for
  loopback tests and brokerless deployments;
- ``pack_gst_mqtt_message`` / ``parse_gst_mqtt_message`` — the reference
  header layout, byte-exact (num_mems, size_mems[16], base/sent epochs,
  duration/dts/pts, 512-byte caps string, 1024 bytes total), so streams
  interop with reference mqttsink/mqttsrc peers.

QoS0 is the stream default (tensor streams are latest-wins, matching
the reference's default); QoS1 (packet id + PUBACK + DUP retransmit)
is available per publish/subscribe for control-plane topics, with
client auto-reconnect/resubscribe and active keepalive failure
detection mirroring the reference's paho MQTTAsync options
(gst/mqtt/mqttsink.c).
"""

from __future__ import annotations

import socket
import struct
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.pipeline import faults as _faults

log = get_logger("mqtt")

# MQTT 3.1.1 control packet types (spec table 2.1)
CONNECT = 1
CONNACK = 2
PUBLISH = 3
PUBACK = 4
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14

PROTOCOL_NAME = b"\x00\x04MQTT"
PROTOCOL_LEVEL = 4  # 3.1.1


# ---------------------------------------------------------------------------
# Packet codec
# ---------------------------------------------------------------------------

def encode_varlen(n: int) -> bytes:
    """Remaining-length varint (spec 2.2.3), 1-4 bytes."""
    if not 0 <= n <= 268_435_455:
        raise ValueError(f"mqtt: remaining length {n} out of range")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decode_varlen(data: bytes, offset: int = 0) -> Tuple[int, int]:
    """→ (value, bytes consumed); raises on malformed/truncated input."""
    value = 0
    for i in range(4):
        if offset + i >= len(data):
            raise ValueError("mqtt: truncated remaining length")
        byte = data[offset + i]
        value |= (byte & 0x7F) << (7 * i)
        if not byte & 0x80:
            return value, i + 1
    raise ValueError("mqtt: malformed remaining length")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varlen(len(body)) + body


def connect_packet(client_id: str, keepalive: int = 60,
                   clean_session: bool = True) -> bytes:
    flags = 0x02 if clean_session else 0x00
    body = (PROTOCOL_NAME + bytes([PROTOCOL_LEVEL, flags]) +
            struct.pack(">H", keepalive) + _utf8(client_id))
    return _packet(CONNECT, 0, body)


def connack_packet(return_code: int = 0,
                   session_present: bool = False) -> bytes:
    return _packet(CONNACK, 0,
                   bytes([1 if session_present else 0, return_code]))


def publish_packet(topic: str, payload: bytes, retain: bool = False,
                   qos: int = 0, packet_id: Optional[int] = None,
                   dup: bool = False) -> bytes:
    """PUBLISH. QoS0 carries no packet id (spec 3.3.2.2); QoS1 requires
    one and may set DUP on retransmission (3.3.1.1)."""
    flags = (0x01 if retain else 0) | ((qos & 0x03) << 1) | \
        (0x08 if dup else 0)
    body = _utf8(topic)
    if qos:
        if packet_id is None:
            raise ValueError("mqtt: QoS>0 PUBLISH needs a packet id")
        body += struct.pack(">H", packet_id)
    return _packet(PUBLISH, flags, body + payload)


def puback_packet(packet_id: int) -> bytes:
    return _packet(PUBACK, 0, struct.pack(">H", packet_id))


def subscribe_packet(packet_id: int, topic_filter: str,
                     qos: int = 0) -> bytes:
    body = struct.pack(">H", packet_id) + _utf8(topic_filter) + bytes([qos])
    return _packet(SUBSCRIBE, 0x02, body)  # reserved flags 0010 (3.8.1)


def suback_packet(packet_id: int, return_codes: List[int]) -> bytes:
    return _packet(SUBACK, 0,
                   struct.pack(">H", packet_id) + bytes(return_codes))


def unsubscribe_packet(packet_id: int, topic_filter: str) -> bytes:
    return _packet(UNSUBSCRIBE, 0x02,
                   struct.pack(">H", packet_id) + _utf8(topic_filter))


def unsuback_packet(packet_id: int) -> bytes:
    return _packet(UNSUBACK, 0, struct.pack(">H", packet_id))


def pingreq_packet() -> bytes:
    return _packet(PINGREQ, 0, b"")


def pingresp_packet() -> bytes:
    return _packet(PINGRESP, 0, b"")


def disconnect_packet() -> bytes:
    return _packet(DISCONNECT, 0, b"")


def read_packet(sock: socket.socket) -> Optional[Tuple[int, int, bytes]]:
    """Blocking read of one packet → (type, flags, body) or None on EOF."""
    first = _read_exact(sock, 1)
    if first is None:
        return None
    ptype, flags = first[0] >> 4, first[0] & 0x0F
    length = 0
    for i in range(4):
        b = _read_exact(sock, 1)
        if b is None:
            return None
        length |= (b[0] & 0x7F) << (7 * i)
        if not b[0] & 0x80:
            break
    else:
        raise ValueError("mqtt: malformed remaining length")
    body = _read_exact(sock, length) if length else b""
    if body is None:
        return None
    return ptype, flags, body


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_publish(flags: int, body: bytes
                  ) -> Tuple[str, bytes, bool, int, Optional[int]]:
    """→ (topic, payload, retain, qos, packet_id)."""
    (tlen,) = struct.unpack_from(">H", body)
    topic = body[2:2 + tlen].decode()
    off = 2 + tlen
    qos = (flags >> 1) & 0x03
    pid = None
    if qos:
        (pid,) = struct.unpack_from(">H", body, off)
        off += 2
    return topic, body[off:], bool(flags & 0x01), qos, pid


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT topic-filter matching: ``+`` one level, ``#`` rest (4.7.1)."""
    p_parts = pattern.split("/")
    t_parts = topic.split("/")
    for i, p in enumerate(p_parts):
        if p == "#":
            return True
        if i >= len(t_parts):
            return False
        if p != "+" and p != t_parts[i]:
            return False
    return len(p_parts) == len(t_parts)


# ---------------------------------------------------------------------------
# GstMQTTMessageHdr — reference wire layout (mqttcommon.h:49-63)
# ---------------------------------------------------------------------------

GST_MQTT_MAX_NUM_MEMS = 16
GST_MQTT_MAX_LEN_GST_CAPS_STR = 512
GST_MQTT_LEN_MSG_HDR = 1024
GST_CLOCK_TIME_NONE = 0xFFFFFFFFFFFFFFFF

#: guint num_mems; (4-pad to align gsize); gsize size_mems[16];
#: gint64 base/sent epochs; GstClockTime duration, dts, pts;
#: gchar gst_caps_str[512] — then reserved up to 1024.
_HDR = struct.Struct("<I4x16QqqQQQ512s")


def pack_gst_mqtt_message(mems: List[bytes], caps_str: str,
                          base_time_epoch: int, sent_time_epoch: int,
                          pts: Optional[int] = None,
                          dts: Optional[int] = None,
                          duration: Optional[int] = None) -> bytes:
    """Reference-format message: 1024-byte header + raw memory blocks
    (mqttsink.c's publish payload)."""
    if len(mems) > GST_MQTT_MAX_NUM_MEMS:
        raise ValueError(
            f"mqtt: {len(mems)} memories exceed "
            f"GST_MQTT_MAX_NUM_MEMS={GST_MQTT_MAX_NUM_MEMS}")
    caps_b = caps_str.encode()
    if len(caps_b) >= GST_MQTT_MAX_LEN_GST_CAPS_STR:
        raise ValueError(
            f"mqtt: caps string {len(caps_b)}B exceeds "
            f"{GST_MQTT_MAX_LEN_GST_CAPS_STR - 1}")
    sizes = [len(m) for m in mems] + [0] * (GST_MQTT_MAX_NUM_MEMS - len(mems))

    def ct(v):
        return GST_CLOCK_TIME_NONE if v is None else int(v)

    hdr = _HDR.pack(len(mems), *sizes, int(base_time_epoch),
                    int(sent_time_epoch), ct(duration), ct(dts), ct(pts),
                    caps_b)
    hdr += b"\x00" * (GST_MQTT_LEN_MSG_HDR - len(hdr))
    return hdr + b"".join(mems)


def parse_gst_mqtt_message(data: bytes) -> dict:
    """→ dict(mems, caps_str, base_time_epoch, sent_time_epoch, pts, dts,
    duration); inverse of :func:`pack_gst_mqtt_message`."""
    if len(data) < GST_MQTT_LEN_MSG_HDR:
        raise ValueError(
            f"mqtt: message {len(data)}B shorter than the "
            f"{GST_MQTT_LEN_MSG_HDR}B GstMQTTMessageHdr")
    fields = _HDR.unpack_from(data)
    num_mems = fields[0]
    if num_mems > GST_MQTT_MAX_NUM_MEMS:
        raise ValueError(f"mqtt: num_mems {num_mems} out of range")
    sizes = fields[1:1 + GST_MQTT_MAX_NUM_MEMS][:num_mems]
    base_epoch, sent_epoch, duration, dts, pts = fields[17:22]
    caps_str = fields[22].split(b"\x00", 1)[0].decode(errors="replace")
    mems = []
    off = GST_MQTT_LEN_MSG_HDR
    for s in sizes:
        if off + s > len(data):
            raise ValueError("mqtt: memory sizes exceed message length")
        mems.append(data[off:off + s])
        off += s

    def ct(v):
        return None if v == GST_CLOCK_TIME_NONE else v

    return dict(mems=mems, caps_str=caps_str, base_time_epoch=base_epoch,
                sent_time_epoch=sent_epoch, pts=ct(pts), dts=ct(dts),
                duration=ct(duration))


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class MqttClient:
    """MQTT 3.1.1 client (QoS0/QoS1 pub/sub, retain, auto-reconnect)
    with the same surface as the shim's ``Client`` so the pubsub
    elements can swap transports via ``broker=mqtt://host:port``.

    QoS1 publishes keep a packet-id→message in-flight map and
    retransmit with DUP until PUBACK (spec 4.4, at-least-once — tensor
    subscribers are latest-wins, so duplicates are harmless). The
    client auto-reconnects with exponential backoff, re-issues every
    subscription, and resends unacked QoS1 messages (paho
    ``MQTTAsync``-style recovery, gst/mqtt/mqttsink.c options).
    Keepalive failure is detected actively: a PINGREQ with no PINGRESP
    within 1.5x the ping interval marks the connection dead
    [MQTT-3.1.2-24]."""

    #: QoS1 in-flight cap: past this, the oldest unacked message is
    #: abandoned (logged) rather than the map growing without bound
    MAX_UNACKED = 256
    #: keepalive-tick retransmits per message before giving up on a
    #: peer that never PUBACKs
    MAX_RETRANSMITS = 16

    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 client_id: Optional[str] = None, keepalive: int = 60,
                 timeout: float = 10.0, reconnect: bool = True,
                 max_reconnect_attempts: int = 8):
        self.failed = threading.Event()
        self._host, self._port = host, port
        self._timeout = timeout
        self._keepalive = keepalive
        self._reconnect = reconnect
        self._max_attempts = max_reconnect_attempts
        #: (topic filter, callback, requested qos)
        self._subs: List[Tuple[str, Callable[[str, bytes], None], int]] = []
        self._lock = threading.Lock()
        self._pid = 0
        #: pid → (done-event, one-slot codes list, topic filter) per
        #: subscribe() awaiting its own SUBACK — correlated by packet id
        #: so the N resubscribe SUBACKs emitted during _recover can't
        #: satisfy a concurrent subscribe() or leak another
        #: subscription's return codes; the filter lets a successful
        #: resubscribe complete a waiter whose own SUBSCRIBE was lost to
        #: the link drop
        self._pending_subacks: Dict[int, tuple] = {}
        #: pid → topic filter for _recover resubscribes (failure logging)
        self._resub_pids: Dict[int, str] = {}
        #: QoS1 in flight: pid → [topic, payload, retain, done-event,
        #: retransmit-count, status("pending"/"acked"/"abandoned")];
        #: bounded so fire-and-forget publishes against a never-PUBACKing
        #: peer can't grow memory forever
        self._unacked: Dict[int, list] = {}
        self._cid = client_id or f"nnstpu-{uuid.uuid4().hex[:12]}"
        self._pong_at = time.monotonic()
        self._ping_at = 0.0
        self.reconnects = 0  # observable recovery count
        self._sock = self._connect()
        self._alive = True
        self._stop_evt = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="mqtt-client-read")
        self._reader.start()
        # keepalive: a conformant broker drops clients silent for
        # 1.5x the advertised interval [MQTT-3.1.2-24]; we ping at half
        # and treat a missing PINGRESP as a dead link
        self._pinger = threading.Thread(
            target=self._ping_loop, args=(max(0.5, keepalive / 2),),
            daemon=True, name="mqtt-client-ping")
        self._pinger.start()

    # -- connection management ------------------------------------------

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        sock = socket.create_connection((self._host, self._port),
                                        timeout=timeout or self._timeout)
        sock.settimeout(self._timeout)
        sock.sendall(connect_packet(self._cid, self._keepalive))
        pkt = read_packet(sock)
        if pkt is None or pkt[0] != CONNACK or pkt[2][1] != 0:
            sock.close()
            raise ConnectionError(
                f"mqtt: CONNECT to {self._host}:{self._port} refused "
                f"(code {pkt[2][1] if pkt else 'EOF'})")
        sock.settimeout(None)
        # bounded SENDS without touching recv: a half-open peer whose
        # window closed must fail a sendall (freeing self._lock) instead
        # of wedging the pinger/publishers forever. "ll" matches struct
        # timeval only where the kernel reads two native-long-sized
        # fields (Linux; LP64 little-endian macOS reads tv_usec from the
        # low half of the second long, which also works); on platforms
        # where the layout is unknown, skip the option rather than pack
        # garbage into setsockopt
        if sys.platform.startswith(("linux", "darwin")):
            tv = struct.pack("ll", int(self._timeout),
                             int(self._timeout % 1 * 1e6))
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        # under the lock: a reconnect racing ping() (which stamps
        # _ping_at under the lock) could otherwise leave a stale
        # _ping_at > _pong_at pair and make the fresh link look
        # half-open on the pinger's very next staleness check
        with self._lock:
            self._pong_at = time.monotonic()
            self._ping_at = 0.0
        return sock

    def _recover(self) -> bool:
        """Reconnect with backoff; resubscribe and resend unacked QoS1
        (DUP set). Returns False when attempts are exhausted — only
        then does ``failed`` latch."""
        try:
            self._sock.close()  # reap the dead fd before replacing it
        except OSError:
            pass
        for attempt in range(self._max_attempts):
            if not self._alive:
                return False
            delay = min(2.0 ** attempt * 0.05, 2.0)
            if self._stop_evt.wait(delay):
                return False
            try:
                # bounded per-attempt connect so `failed` latches within
                # seconds, not minutes, when the broker is unreachable
                sock = self._connect(timeout=min(self._timeout, 2.0))
            except (OSError, ConnectionError) as e:  # incl. CONNACK refusal
                log.info("mqtt: reconnect attempt %d failed: %s",
                         attempt + 1, e)
                continue
            # publish the socket, resubscribe, and resend unacked while
            # holding the lock: app publishers / the pinger must not
            # interleave writes mid-recovery on the fresh socket
            with self._lock:
                self._sock = sock
                subs = list(self._subs)
                unacked = list(self._unacked.items())
                try:
                    self._resub_pids.clear()
                    for filt, _cb, qos in subs:
                        self._pid = self._pid % 0xFFFF + 1
                        self._resub_pids[self._pid] = filt
                        sock.sendall(subscribe_packet(self._pid, filt,  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                                                      qos=qos))
                    for pid, (topic, payload, retain,
                              *_rest) in unacked:
                        sock.sendall(publish_packet(topic, payload, retain,  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                                                    qos=1, packet_id=pid,
                                                    dup=True))
                except OSError:
                    try:
                        sock.close()  # don't leak the half-set-up socket
                    except OSError:
                        pass
                    continue
            self.reconnects += 1
            log.info("mqtt: reconnected to %s:%d (attempt %d, %d subs, "
                     "%d unacked resent)", self._host, self._port,
                     attempt + 1, len(subs), len(unacked))
            return True
        return False

    def _on_link_down(self) -> bool:
        """Shared failure path for reader EOF and keepalive timeout."""
        if not self._alive:
            return False
        if self._reconnect and self._recover():
            return True
        if self._alive:  # a close() mid-recovery is not a failure
            self.failed.set()
        return False

    def _ping_loop(self, interval: float):
        while not self._stop_evt.wait(interval):
            if not self._alive:
                return
            now = time.monotonic()
            if self._ping_at and self._pong_at < self._ping_at and \
                    now - self._ping_at > 1.5 * interval:
                # PINGREQ went unanswered: the link is dead even though
                # the socket may still look open (half-open TCP)
                log.warning("mqtt: keepalive timeout (no PINGRESP)")
                try:
                    # shutdown (not just close) unblocks the reader,
                    # which owns the reconnect
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._sock.close()
                except OSError:
                    pass
                continue
            try:
                self.ping()
            except OSError:
                pass  # reader sees the dead socket and recovers
            # background at-least-once: resend unacked QoS1 with DUP each
            # keepalive tick (covers fire-and-forget publishes too), but
            # give up after MAX_RETRANSMITS — a peer that never PUBACKs
            # must not cost bandwidth forever
            with self._lock:
                for pid in list(self._unacked):
                    entry = self._unacked[pid]
                    if entry[4] >= self.MAX_RETRANSMITS:
                        del self._unacked[pid]
                        entry[5] = "abandoned"
                        entry[3].set()  # wake a blocked publish() waiter
                        log.warning(
                            "mqtt: abandoning QoS1 packet %d to %r after "
                            "%d retransmits without PUBACK", pid, entry[0],
                            entry[4])
                        continue
                    entry[4] += 1
                    try:
                        self._sock.sendall(publish_packet(  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                            entry[0], entry[1], entry[2], qos=1,
                            packet_id=pid, dup=True))
                    except OSError:
                        break

    # -- pub/sub ---------------------------------------------------------

    def publish(self, topic: str, payload: bytes, retain: bool = False,
                qos: int = 0, timeout: Optional[float] = None) -> None:
        """Publish. ``qos=1``: blocks until PUBACK when ``timeout`` is
        given; without one it returns immediately and the keepalive
        loop retransmits (DUP) each tick until PUBACK."""
        act = None
        fi = _faults.ACTIVE
        if fi is not None:
            act = fi.action("mqtt.publish")
            if act == "disconnect":
                # sever the broker link; the keepalive loop's reconnect
                # path owns recovery (QoS1 unacked entries retransmit,
                # QoS0 is lost — the at-most-once contract)
                try:
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            elif act == "corrupt":
                # a reserved packet type (0xF0): any compliant broker
                # must drop the connection on it (MQTT-2.2.2-2)
                with self._lock:
                    try:
                        self._sock.sendall(b"\xf0\x00")  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                    except OSError:
                        pass
        if qos == 0:
            if act is None:
                with self._lock:
                    self._sock.sendall(publish_packet(topic, payload, retain))  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
            return
        if qos != 1:
            raise ValueError("mqtt: only QoS 0/1 supported")
        evt = threading.Event()
        with self._lock:
            if len(self._unacked) >= self.MAX_UNACKED:
                old_pid = next(iter(self._unacked))
                old = self._unacked.pop(old_pid)
                old[5] = "abandoned"
                old[3].set()  # wake a blocked publish() waiter
                log.warning(
                    "mqtt: QoS1 backlog full (%d); abandoning oldest "
                    "unacked packet %d to %r", self.MAX_UNACKED, old_pid,
                    old[0])
            self._pid = self._pid % 0xFFFF + 1
            pid = self._pid
            entry = [topic, payload, retain, evt, 0, "pending"]
            self._unacked[pid] = entry
            if act is None:  # a dropped first copy recovers via DUP
                # retransmit — the entry above is already in _unacked
                self._sock.sendall(publish_packet(topic, payload, retain,  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                                                  qos=1, packet_id=pid))
        if timeout is not None:
            deadline = time.monotonic() + timeout
            while not evt.wait(0.25):
                if time.monotonic() > deadline:
                    with self._lock:
                        if evt.is_set():  # PUBACK landed in the gap
                            break
                        # the caller is told delivery failed — stop
                        # retransmitting a message they will re-send
                        self._unacked.pop(pid, None)
                    raise TimeoutError(
                        f"mqtt: no PUBACK for packet {pid} within "
                        f"{timeout}s")
                with self._lock:
                    # retransmit only while still in flight: an entry
                    # the keepalive loop abandoned must stop costing
                    # bandwidth here too
                    if pid in self._unacked:
                        try:  # retransmit with DUP while waiting
                            self._sock.sendall(publish_packet(  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                                topic, payload, retain, qos=1,
                                packet_id=pid, dup=True))
                        except OSError:
                            pass
            if entry[5] != "acked":
                raise ConnectionError(
                    f"mqtt: QoS1 packet {pid} abandoned after "
                    f"{entry[4]} retransmits without PUBACK")

    def subscribe(self, topic_filter: str,
                  cb: Callable[[str, bytes], None],
                  timeout: float = 10.0, qos: int = 0) -> None:
        """Subscribe. Tensor streams default to QoS0 (latest-wins, no
        broker-side tracking); pass ``qos=1`` for control topics."""
        evt = threading.Event()
        slot: list = [None]  # SUBACK return codes land here, by pid
        with self._lock:
            self._pid = self._pid % 0xFFFF + 1
            pid = self._pid
            self._subs.append((topic_filter, cb, qos))
            self._pending_subacks[pid] = (evt, slot, topic_filter)
            self._sock.sendall(subscribe_packet(pid, topic_filter,  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                                                qos=qos))
        try:
            if not evt.wait(timeout):
                raise ConnectionError(
                    f"mqtt: no SUBACK for {topic_filter!r}")
        finally:
            with self._lock:
                self._pending_subacks.pop(pid, None)
        codes = slot[0] or b""
        if any(c == 0x80 for c in codes):  # spec 3.9.3: 0x80 = failure
            with self._lock:
                self._subs.remove((topic_filter, cb, qos))
            raise ConnectionError(
                f"mqtt: broker rejected subscription to {topic_filter!r}")

    def _read_loop(self):
        while self._alive:
            try:
                pkt = read_packet(self._sock)
            except Exception:
                pkt = None
            if pkt is None:
                if self._on_link_down():
                    continue
                return
            ptype, flags, body = pkt
            try:
                if ptype == PUBLISH:
                    topic, payload, _retain, qos, pid = \
                        parse_publish(flags, body)
                    if qos and pid is not None:
                        with self._lock:
                            self._sock.sendall(puback_packet(pid))  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
                    # copy under the lock (subscribe()/unsubscribe run on
                    # other threads), dispatch outside it
                    with self._lock:
                        subs = list(self._subs)
                    for pattern, cb, _q in subs:
                        if topic_matches(pattern, topic):
                            try:
                                cb(topic, payload)
                            except Exception as e:  # noqa: BLE001
                                log.warning("mqtt subscriber callback: %s", e)
                elif ptype == PUBACK:
                    (pid,) = struct.unpack_from(">H", body)
                    with self._lock:
                        entry = self._unacked.pop(pid, None)
                    if entry is not None:
                        entry[5] = "acked"
                        entry[3].set()
                elif ptype == SUBACK:
                    (pid,) = struct.unpack_from(">H", body)
                    codes = body[2:]
                    with self._lock:
                        waiters = []
                        w = self._pending_subacks.get(pid)
                        if w is not None:
                            waiters.append(w)
                        refilt = self._resub_pids.pop(pid, None)
                        if refilt is not None:
                            # a subscribe() whose own SUBSCRIBE was lost
                            # to the link drop is satisfied by _recover's
                            # resubscribe of the same filter
                            waiters.extend(
                                pw for pw in
                                self._pending_subacks.values()
                                if pw[2] == refilt and pw is not w)
                    for evt_, slot_, _filt in waiters:
                        slot_[0] = codes
                        evt_.set()
                    if refilt is not None and not waiters and \
                            any(c == 0x80 for c in codes):
                        log.warning("mqtt: broker rejected resubscription"
                                    " to %r", refilt)
                elif ptype == PINGRESP:
                    # under the lock: the pinger compares _pong_at
                    # against _ping_at as one pair under it
                    with self._lock:
                        self._pong_at = time.monotonic()
                elif ptype == PINGREQ:
                    with self._lock:
                        self._sock.sendall(pingresp_packet())  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
            except Exception as e:  # noqa: BLE001 — malformed peer bytes
                # framing state is unreliable past a parse error: fail the
                # connection so pollers of `failed` see it, don't hang
                log.warning("mqtt: malformed packet type %d: %s", ptype, e)
                if self._on_link_down():
                    continue
                return

    def ping(self) -> None:
        with self._lock:
            self._ping_at = time.monotonic()
            self._sock.sendall(pingreq_packet())  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them

    def close(self) -> None:
        self._alive = False
        self._stop_evt.set()
        try:
            with self._lock:
                self._sock.sendall(disconnect_packet())  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them
        except OSError:
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


# ---------------------------------------------------------------------------
# Broker
# ---------------------------------------------------------------------------

class MqttBroker:
    """In-process broker speaking real MQTT 3.1.1 (QoS0/QoS1 + retain).

    Gives loopback tests and brokerless edge deployments a conformant
    peer; production fleets point ``broker=mqtt://`` at their own.
    Incoming QoS1 publishes are PUBACKed; deliveries to QoS1
    subscribers carry packet ids and are retransmitted (DUP) by a sweep
    thread until the subscriber PUBACKs."""

    _RETX_INTERVAL = 1.0  # seconds between QoS1 redelivery sweeps

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        #: sock → list of (topic filter, granted qos)
        self._clients: Dict[socket.socket, List[Tuple[str, int]]] = {}
        self._retained: Dict[str, bytes] = {}
        #: sock → {pid: (topic, payload, retain)} awaiting PUBACK
        self._inflight: Dict[socket.socket, Dict[int, tuple]] = {}
        #: sock → write lock: handler threads, _route callers, and the
        #: retransmit sweeper all write to subscriber sockets — without
        #: per-socket serialization their frames would interleave
        self._wlocks: Dict[socket.socket, threading.Lock] = {}
        self._next_pid = 0
        self._alive = True
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True, name="mqtt-accept")
        self._acceptor.start()
        self._sweeper = threading.Thread(target=self._retx_loop,
                                         daemon=True, name="mqtt-retx")
        self._sweeper.start()

    def _send(self, sock: socket.socket, data: bytes) -> None:
        with self._lock:
            wlock = self._wlocks.get(sock)
        if wlock is None:
            sock.sendall(data)  # pre-registration (CONNACK): single-owner
            return
        with wlock:
            sock.sendall(data)  # nns-lint: disable=NNS102,NNS112 -- the lock serializes writes to this socket; SO_SNDTIMEO (set at connect) bounds them

    def _retx_loop(self):
        while self._alive:
            time.sleep(self._RETX_INTERVAL)
            with self._lock:
                work = [(s, dict(m)) for s, m in self._inflight.items() if m]
            for sock, msgs in work:
                for pid, (topic, payload, retain) in msgs.items():
                    try:
                        self._send(sock, publish_packet(
                            topic, payload, retain, qos=1, packet_id=pid,
                            dup=True))
                    except OSError:
                        break

    def _accept_loop(self):
        while self._alive:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,), daemon=True,
                             name="mqtt-serve").start()

    def _serve(self, sock: socket.socket):
        try:
            pkt = read_packet(sock)
            if pkt is None or pkt[0] != CONNECT:
                sock.close()
                return
            body = pkt[2]
            if body[:6] != PROTOCOL_NAME or body[6] != PROTOCOL_LEVEL:
                sock.sendall(connack_packet(return_code=1))  # bad version
                sock.close()
                return
            sock.sendall(connack_packet(0))
            with self._lock:
                self._clients[sock] = []
                self._inflight[sock] = {}
                self._wlocks[sock] = threading.Lock()
            while self._alive:
                pkt = read_packet(sock)
                if pkt is None:
                    break
                ptype, flags, body = pkt
                if ptype == PUBLISH:
                    topic, payload, retain, qos, pid = \
                        parse_publish(flags, body)
                    if qos and pid is not None:
                        self._send(sock, puback_packet(pid))
                    self._route(topic, payload, retain)
                elif ptype == PUBACK:
                    (pid,) = struct.unpack_from(">H", body)
                    with self._lock:
                        self._inflight.get(sock, {}).pop(pid, None)
                elif ptype == SUBSCRIBE:
                    (pid,) = struct.unpack_from(">H", body)
                    off, codes = 2, []
                    with self._lock:
                        filters = self._clients.get(sock)
                    while off < len(body):
                        (tlen,) = struct.unpack_from(">H", body, off)
                        filt = body[off + 2:off + 2 + tlen].decode()
                        req_qos = body[off + 2 + tlen] & 0x03
                        off += 2 + tlen + 1
                        granted = min(req_qos, 1)
                        codes.append(granted)
                        if filters is not None:
                            filters.append((filt, granted))
                        self._send_retained(sock, filt)
                    self._send(sock, suback_packet(pid, codes))
                elif ptype == UNSUBSCRIBE:
                    (pid,) = struct.unpack_from(">H", body)
                    (tlen,) = struct.unpack_from(">H", body, 2)
                    filt = body[4:4 + tlen].decode()
                    with self._lock:
                        subs = self._clients.get(sock, [])
                        self._clients[sock] = [
                            (f, q) for f, q in subs if f != filt]
                    self._send(sock, unsuback_packet(pid))
                elif ptype == PINGREQ:
                    self._send(sock, pingresp_packet())
                elif ptype == DISCONNECT:
                    break
        except OSError:
            pass
        finally:
            with self._lock:
                self._clients.pop(sock, None)
                self._inflight.pop(sock, None)
                self._wlocks.pop(sock, None)
            sock.close()

    def _send_retained(self, sock: socket.socket, filt: str):
        with self._lock:
            hits = [(t, p) for t, p in self._retained.items()
                    if topic_matches(filt, t)]
        for topic, payload in hits:
            try:
                self._send(sock, publish_packet(topic, payload,
                                                retain=True))
            except OSError:
                pass

    def _route(self, topic: str, payload: bytes, retain: bool):
        with self._lock:
            if retain:
                if payload:
                    self._retained[topic] = payload
                else:
                    self._retained.pop(topic, None)  # spec 3.3.1.3
            targets = []  # (sock, delivery qos)
            for s, filters in self._clients.items():
                qs = [q for f, q in filters if topic_matches(f, topic)]
                if qs:
                    targets.append((s, max(qs)))
            qos1 = []
            for s, q in targets:
                if q:
                    self._next_pid = self._next_pid % 0xFFFF + 1
                    pid = self._next_pid
                    # live deliveries carry retain=0 [MQTT-3.3.1-9];
                    # only _send_retained sets the flag
                    self._inflight.setdefault(s, {})[pid] = \
                        (topic, payload, False)
                    qos1.append((s, pid))
        pkt0 = publish_packet(topic, payload)
        for s, q in targets:
            if q:
                continue
            try:
                self._send(s, pkt0)
            except OSError:
                pass
        for s, pid in qos1:
            try:
                self._send(s, publish_packet(topic, payload, retain=False,
                                             qos=1, packet_id=pid))
            except OSError:
                pass  # the sweep retries until the reader reaps the sock

    def close(self) -> None:
        self._alive = False
        # shutdown() before close(): close() alone does not wake a
        # recv()/accept() blocked in another thread
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._clients)
            self._clients.clear()
            self._inflight.clear()
            self._wlocks.clear()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
