"""MQTT 3.1.1 — real protocol framing for the pubsub elements.

Reference: ``gst/mqtt/mqttsink.c`` / ``mqttsrc.c`` speak MQTT through
paho; their payloads prepend the fixed 1024-byte ``GstMQTTMessageHdr``
(``gst/mqtt/mqttcommon.h:49-63``) so any subscriber can reconstruct the
buffer. This module provides the same capability without paho:

- **packet codec** — CONNECT/CONNACK/SUBSCRIBE/SUBACK/PUBLISH(QoS0,
  retain)/PING*/DISCONNECT encode+decode per the MQTT 3.1.1 spec
  (unit-tested always; any conformant broker understands them);
- :class:`MqttClient` — a minimal client (same surface as the in-process
  shim's ``Client``) usable against any broker reachable at
  ``mqtt://host:port``;
- :class:`MqttBroker` — an in-process broker speaking real MQTT, for
  loopback tests and brokerless deployments;
- ``pack_gst_mqtt_message`` / ``parse_gst_mqtt_message`` — the reference
  header layout, byte-exact (num_mems, size_mems[16], base/sent epochs,
  duration/dts/pts, 512-byte caps string, 1024 bytes total), so streams
  interop with reference mqttsink/mqttsrc peers.

QoS0-only by design: tensor streams are latest-wins; the reference's
default QoS for streams is 0 as well, and retransmit logic belongs to
the query protocol (which has in-flight windows), not here.
"""

from __future__ import annotations

import socket
import struct
import threading
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger

log = get_logger("mqtt")

# MQTT 3.1.1 control packet types (spec table 2.1)
CONNECT = 1
CONNACK = 2
PUBLISH = 3
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14

PROTOCOL_NAME = b"\x00\x04MQTT"
PROTOCOL_LEVEL = 4  # 3.1.1


# ---------------------------------------------------------------------------
# Packet codec
# ---------------------------------------------------------------------------

def encode_varlen(n: int) -> bytes:
    """Remaining-length varint (spec 2.2.3), 1-4 bytes."""
    if not 0 <= n <= 268_435_455:
        raise ValueError(f"mqtt: remaining length {n} out of range")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decode_varlen(data: bytes, offset: int = 0) -> Tuple[int, int]:
    """→ (value, bytes consumed); raises on malformed/truncated input."""
    value = 0
    for i in range(4):
        if offset + i >= len(data):
            raise ValueError("mqtt: truncated remaining length")
        byte = data[offset + i]
        value |= (byte & 0x7F) << (7 * i)
        if not byte & 0x80:
            return value, i + 1
    raise ValueError("mqtt: malformed remaining length")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varlen(len(body)) + body


def connect_packet(client_id: str, keepalive: int = 60,
                   clean_session: bool = True) -> bytes:
    flags = 0x02 if clean_session else 0x00
    body = (PROTOCOL_NAME + bytes([PROTOCOL_LEVEL, flags]) +
            struct.pack(">H", keepalive) + _utf8(client_id))
    return _packet(CONNECT, 0, body)


def connack_packet(return_code: int = 0,
                   session_present: bool = False) -> bytes:
    return _packet(CONNACK, 0,
                   bytes([1 if session_present else 0, return_code]))


def publish_packet(topic: str, payload: bytes, retain: bool = False) -> bytes:
    """QoS0 PUBLISH (no packet id in QoS0, spec 3.3.2.2)."""
    return _packet(PUBLISH, 0x01 if retain else 0x00,
                   _utf8(topic) + payload)


def subscribe_packet(packet_id: int, topic_filter: str,
                     qos: int = 0) -> bytes:
    body = struct.pack(">H", packet_id) + _utf8(topic_filter) + bytes([qos])
    return _packet(SUBSCRIBE, 0x02, body)  # reserved flags 0010 (3.8.1)


def suback_packet(packet_id: int, return_codes: List[int]) -> bytes:
    return _packet(SUBACK, 0,
                   struct.pack(">H", packet_id) + bytes(return_codes))


def unsubscribe_packet(packet_id: int, topic_filter: str) -> bytes:
    return _packet(UNSUBSCRIBE, 0x02,
                   struct.pack(">H", packet_id) + _utf8(topic_filter))


def unsuback_packet(packet_id: int) -> bytes:
    return _packet(UNSUBACK, 0, struct.pack(">H", packet_id))


def pingreq_packet() -> bytes:
    return _packet(PINGREQ, 0, b"")


def pingresp_packet() -> bytes:
    return _packet(PINGRESP, 0, b"")


def disconnect_packet() -> bytes:
    return _packet(DISCONNECT, 0, b"")


def read_packet(sock: socket.socket) -> Optional[Tuple[int, int, bytes]]:
    """Blocking read of one packet → (type, flags, body) or None on EOF."""
    first = _read_exact(sock, 1)
    if first is None:
        return None
    ptype, flags = first[0] >> 4, first[0] & 0x0F
    length = 0
    for i in range(4):
        b = _read_exact(sock, 1)
        if b is None:
            return None
        length |= (b[0] & 0x7F) << (7 * i)
        if not b[0] & 0x80:
            break
    else:
        raise ValueError("mqtt: malformed remaining length")
    body = _read_exact(sock, length) if length else b""
    if body is None:
        return None
    return ptype, flags, body


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_publish(flags: int, body: bytes) -> Tuple[str, bytes, bool]:
    """→ (topic, payload, retain). QoS>0 carries a packet id we skip."""
    (tlen,) = struct.unpack_from(">H", body)
    topic = body[2:2 + tlen].decode()
    off = 2 + tlen
    qos = (flags >> 1) & 0x03
    if qos:
        off += 2
    return topic, body[off:], bool(flags & 0x01)


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT topic-filter matching: ``+`` one level, ``#`` rest (4.7.1)."""
    p_parts = pattern.split("/")
    t_parts = topic.split("/")
    for i, p in enumerate(p_parts):
        if p == "#":
            return True
        if i >= len(t_parts):
            return False
        if p != "+" and p != t_parts[i]:
            return False
    return len(p_parts) == len(t_parts)


# ---------------------------------------------------------------------------
# GstMQTTMessageHdr — reference wire layout (mqttcommon.h:49-63)
# ---------------------------------------------------------------------------

GST_MQTT_MAX_NUM_MEMS = 16
GST_MQTT_MAX_LEN_GST_CAPS_STR = 512
GST_MQTT_LEN_MSG_HDR = 1024
GST_CLOCK_TIME_NONE = 0xFFFFFFFFFFFFFFFF

#: guint num_mems; (4-pad to align gsize); gsize size_mems[16];
#: gint64 base/sent epochs; GstClockTime duration, dts, pts;
#: gchar gst_caps_str[512] — then reserved up to 1024.
_HDR = struct.Struct("<I4x16QqqQQQ512s")


def pack_gst_mqtt_message(mems: List[bytes], caps_str: str,
                          base_time_epoch: int, sent_time_epoch: int,
                          pts: Optional[int] = None,
                          dts: Optional[int] = None,
                          duration: Optional[int] = None) -> bytes:
    """Reference-format message: 1024-byte header + raw memory blocks
    (mqttsink.c's publish payload)."""
    if len(mems) > GST_MQTT_MAX_NUM_MEMS:
        raise ValueError(
            f"mqtt: {len(mems)} memories exceed "
            f"GST_MQTT_MAX_NUM_MEMS={GST_MQTT_MAX_NUM_MEMS}")
    caps_b = caps_str.encode()
    if len(caps_b) >= GST_MQTT_MAX_LEN_GST_CAPS_STR:
        raise ValueError(
            f"mqtt: caps string {len(caps_b)}B exceeds "
            f"{GST_MQTT_MAX_LEN_GST_CAPS_STR - 1}")
    sizes = [len(m) for m in mems] + [0] * (GST_MQTT_MAX_NUM_MEMS - len(mems))

    def ct(v):
        return GST_CLOCK_TIME_NONE if v is None else int(v)

    hdr = _HDR.pack(len(mems), *sizes, int(base_time_epoch),
                    int(sent_time_epoch), ct(duration), ct(dts), ct(pts),
                    caps_b)
    hdr += b"\x00" * (GST_MQTT_LEN_MSG_HDR - len(hdr))
    return hdr + b"".join(mems)


def parse_gst_mqtt_message(data: bytes) -> dict:
    """→ dict(mems, caps_str, base_time_epoch, sent_time_epoch, pts, dts,
    duration); inverse of :func:`pack_gst_mqtt_message`."""
    if len(data) < GST_MQTT_LEN_MSG_HDR:
        raise ValueError(
            f"mqtt: message {len(data)}B shorter than the "
            f"{GST_MQTT_LEN_MSG_HDR}B GstMQTTMessageHdr")
    fields = _HDR.unpack_from(data)
    num_mems = fields[0]
    if num_mems > GST_MQTT_MAX_NUM_MEMS:
        raise ValueError(f"mqtt: num_mems {num_mems} out of range")
    sizes = fields[1:1 + GST_MQTT_MAX_NUM_MEMS][:num_mems]
    base_epoch, sent_epoch, duration, dts, pts = fields[17:22]
    caps_str = fields[22].split(b"\x00", 1)[0].decode(errors="replace")
    mems = []
    off = GST_MQTT_LEN_MSG_HDR
    for s in sizes:
        if off + s > len(data):
            raise ValueError("mqtt: memory sizes exceed message length")
        mems.append(data[off:off + s])
        off += s

    def ct(v):
        return None if v == GST_CLOCK_TIME_NONE else v

    return dict(mems=mems, caps_str=caps_str, base_time_epoch=base_epoch,
                sent_time_epoch=sent_epoch, pts=ct(pts), dts=ct(dts),
                duration=ct(duration))


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

class MqttClient:
    """Minimal MQTT 3.1.1 client (QoS0 pub/sub, retain) with the same
    surface as the shim's ``Client`` so the pubsub elements can swap
    transports via ``broker=mqtt://host:port``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 client_id: Optional[str] = None, keepalive: int = 60,
                 timeout: float = 10.0):
        self.failed = threading.Event()
        self._subs: List[Tuple[str, Callable[[str, bytes], None]]] = []
        self._lock = threading.Lock()
        self._pid = 0
        self._suback = threading.Event()
        self._suback_codes: Optional[bytes] = None
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        cid = client_id or f"nnstpu-{uuid.uuid4().hex[:12]}"
        self._sock.sendall(connect_packet(cid, keepalive))
        pkt = read_packet(self._sock)
        if pkt is None or pkt[0] != CONNACK or pkt[2][1] != 0:
            self._sock.close()
            raise ConnectionError(
                f"mqtt: CONNECT to {host}:{port} refused "
                f"(code {pkt[2][1] if pkt else 'EOF'})")
        self._sock.settimeout(None)
        self._alive = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="mqtt-client-read")
        self._reader.start()
        # keepalive: a conformant broker drops clients silent for
        # 1.5x the advertised interval [MQTT-3.1.2-24]
        self._stop_evt = threading.Event()
        self._pinger = threading.Thread(
            target=self._ping_loop, args=(max(1.0, keepalive / 2),),
            daemon=True, name="mqtt-client-ping")
        self._pinger.start()

    def _ping_loop(self, interval: float):
        while not self._stop_evt.wait(interval):
            if not self._alive:
                return
            try:
                self.ping()
            except OSError:
                return

    def publish(self, topic: str, payload: bytes,
                retain: bool = False) -> None:
        with self._lock:
            self._sock.sendall(publish_packet(topic, payload, retain))

    def subscribe(self, topic_filter: str,
                  cb: Callable[[str, bytes], None],
                  timeout: float = 10.0) -> None:
        with self._lock:
            self._pid = self._pid % 0xFFFF + 1
            self._subs.append((topic_filter, cb))
            self._suback.clear()
            self._suback_codes = None
            self._sock.sendall(subscribe_packet(self._pid, topic_filter))
        if not self._suback.wait(timeout):
            raise ConnectionError(f"mqtt: no SUBACK for {topic_filter!r}")
        codes = self._suback_codes or b""
        if any(c == 0x80 for c in codes):  # spec 3.9.3: 0x80 = failure
            with self._lock:
                self._subs.remove((topic_filter, cb))
            raise ConnectionError(
                f"mqtt: broker rejected subscription to {topic_filter!r}")

    def _read_loop(self):
        while self._alive:
            try:
                pkt = read_packet(self._sock)
            except Exception:
                pkt = None
            if pkt is None:
                if self._alive:
                    self.failed.set()
                return
            ptype, flags, body = pkt
            try:
                if ptype == PUBLISH:
                    topic, payload, _retain = parse_publish(flags, body)
                    for pattern, cb in list(self._subs):
                        if topic_matches(pattern, topic):
                            try:
                                cb(topic, payload)
                            except Exception as e:  # noqa: BLE001
                                log.warning("mqtt subscriber callback: %s", e)
                elif ptype == SUBACK:
                    self._suback_codes = body[2:]  # skip packet id
                    self._suback.set()
                elif ptype == PINGREQ:
                    with self._lock:
                        self._sock.sendall(pingresp_packet())
            except Exception as e:  # noqa: BLE001 — malformed peer bytes
                # framing state is unreliable past a parse error: fail the
                # connection so pollers of `failed` see it, don't hang
                log.warning("mqtt: malformed packet type %d: %s", ptype, e)
                if self._alive:
                    self.failed.set()
                return

    def ping(self) -> None:
        with self._lock:
            self._sock.sendall(pingreq_packet())

    def close(self) -> None:
        self._alive = False
        self._stop_evt.set()
        try:
            with self._lock:
                self._sock.sendall(disconnect_packet())
        except OSError:
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


# ---------------------------------------------------------------------------
# Broker
# ---------------------------------------------------------------------------

class MqttBroker:
    """In-process broker speaking real MQTT 3.1.1 (QoS0 + retain).

    Gives loopback tests and brokerless edge deployments a conformant
    peer; production fleets point ``broker=mqtt://`` at their own."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        #: sock → list of topic filters
        self._clients: Dict[socket.socket, List[str]] = {}
        self._retained: Dict[str, bytes] = {}
        self._alive = True
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True, name="mqtt-accept")
        self._acceptor.start()

    def _accept_loop(self):
        while self._alive:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(sock,), daemon=True,
                             name="mqtt-serve").start()

    def _serve(self, sock: socket.socket):
        try:
            pkt = read_packet(sock)
            if pkt is None or pkt[0] != CONNECT:
                sock.close()
                return
            body = pkt[2]
            if body[:6] != PROTOCOL_NAME or body[6] != PROTOCOL_LEVEL:
                sock.sendall(connack_packet(return_code=1))  # bad version
                sock.close()
                return
            sock.sendall(connack_packet(0))
            with self._lock:
                self._clients[sock] = []
            while self._alive:
                pkt = read_packet(sock)
                if pkt is None:
                    break
                ptype, flags, body = pkt
                if ptype == PUBLISH:
                    topic, payload, retain = parse_publish(flags, body)
                    self._route(topic, payload, retain)
                elif ptype == SUBSCRIBE:
                    (pid,) = struct.unpack_from(">H", body)
                    off, codes = 2, []
                    with self._lock:
                        filters = self._clients.get(sock)
                    while off < len(body):
                        (tlen,) = struct.unpack_from(">H", body, off)
                        filt = body[off + 2:off + 2 + tlen].decode()
                        off += 2 + tlen + 1  # + requested QoS byte
                        codes.append(0)  # granted QoS0
                        if filters is not None:
                            filters.append(filt)
                        self._send_retained(sock, filt)
                    sock.sendall(suback_packet(pid, codes))
                elif ptype == UNSUBSCRIBE:
                    (pid,) = struct.unpack_from(">H", body)
                    (tlen,) = struct.unpack_from(">H", body, 2)
                    filt = body[4:4 + tlen].decode()
                    with self._lock:
                        if filt in self._clients.get(sock, []):
                            self._clients[sock].remove(filt)
                    sock.sendall(unsuback_packet(pid))
                elif ptype == PINGREQ:
                    sock.sendall(pingresp_packet())
                elif ptype == DISCONNECT:
                    break
        except OSError:
            pass
        finally:
            with self._lock:
                self._clients.pop(sock, None)
            sock.close()

    def _send_retained(self, sock: socket.socket, filt: str):
        with self._lock:
            hits = [(t, p) for t, p in self._retained.items()
                    if topic_matches(filt, t)]
        for topic, payload in hits:
            try:
                sock.sendall(publish_packet(topic, payload, retain=True))
            except OSError:
                pass

    def _route(self, topic: str, payload: bytes, retain: bool):
        with self._lock:
            if retain:
                if payload:
                    self._retained[topic] = payload
                else:
                    self._retained.pop(topic, None)  # spec 3.3.1.3
            targets = [s for s, filters in self._clients.items()
                       if any(topic_matches(f, topic) for f in filters)]
        pkt = publish_packet(topic, payload)
        for s in targets:
            try:
                s.sendall(pkt)
            except OSError:
                pass

    def close(self) -> None:
        self._alive = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._clients)
            self._clients.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
