"""Topic-based pub/sub — the MQTT capability, self-contained.

Reference: ``gst/mqtt/`` (mqttsink.c 1407, mqttsrc.c 1423 LoC) publishes
GStreamer buffers over a paho-MQTT broker with NTP-corrected cross-device
timestamps (``ntputil.c``, Documentation/synchronization-in-mqtt-elements
.md). This stack has no external broker, so the capability is provided
whole: a broker speaking a minimal topic protocol over the same framed
TCP transport as tensor_query, with RETAIN semantics (needed by
discovery) and epoch-carrying buffer frames for cross-host timestamp
rebasing (the ntputil role).

Protocol commands (framed as query.protocol):
  SUB <topic>            — subscribe (wildcard suffix '#' supported)
  PUB <topic> <payload>  — publish; RETAIN bit keeps last payload
  MSG <topic> <payload>  — broker → subscriber delivery
"""

from __future__ import annotations

import json
import queue as _queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query import protocol as P

log = get_logger("pubsub")

# commands layered on the framed transport (distinct magic from query)
_MAGIC = 0x4E505331  # 'NPS1'
CMD_SUB = 1
CMD_PUB = 2
CMD_PUB_RETAIN = 3
CMD_MSG = 4
CMD_BYE = 5

_TOPIC_HDR = struct.Struct("<H")


def _pack_topic(topic: str, payload: bytes) -> bytes:
    t = topic.encode()
    return _TOPIC_HDR.pack(len(t)) + t + payload


def _unpack_topic(data: bytes) -> Tuple[str, bytes]:
    (tlen,) = _TOPIC_HDR.unpack_from(data)
    topic = data[2:2 + tlen].decode()
    return topic, data[2 + tlen:]


def _send(sock, cmd: int, payload: bytes) -> None:
    from nnstreamer_tpu import native

    native.send_frame(sock, _MAGIC, cmd, payload)


def _recv(sock) -> Tuple[int, bytes]:
    hdr = P._recv_exact(sock, 16)
    magic, cmd, plen = struct.unpack("<IIQ", hdr)
    if magic != _MAGIC:
        raise P.QueryProtocolError(f"pubsub: bad magic {magic:#x}")
    payload = P._recv_exact(sock, plen) if plen else b""
    return cmd, payload


def _topic_matches(pattern: str, topic: str) -> bool:
    if pattern.endswith("#"):
        return topic.startswith(pattern[:-1])
    return pattern == topic


class Broker:
    """In-process pub/sub broker (the paho-broker role)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._subs: List[Tuple[str, socket.socket]] = []
        self._retained: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        # per-connection write locks: concurrent publisher threads must not
        # interleave frame bytes on one subscriber socket
        self._wlocks: Dict[socket.socket, threading.Lock] = {}
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Broker":
        self._stop.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(32)
        self._listener.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="pubsub-broker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        if self._listener:
            self._listener.close()
            self._listener = None
        with self._lock:
            for _, s in self._subs:
                try:
                    s.shutdown(socket.SHUT_RDWR)  # force FIN even with a
                    # reader blocked on the fd; close() alone may not
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            self._subs.clear()
            self._wlocks.clear()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = _recv(conn)
                if cmd == CMD_SUB:
                    topic, _ = _unpack_topic(payload)
                    with self._lock:
                        self._subs.append((topic, conn))
                        self._wlocks.setdefault(conn, threading.Lock())
                        retained = [
                            (t, p) for t, p in self._retained.items()
                            if _topic_matches(topic, t)
                        ]
                    for t, p in retained:  # deliver retained immediately
                        self._send_locked(conn, _pack_topic(t, p))
                elif cmd in (CMD_PUB, CMD_PUB_RETAIN):
                    topic, body = _unpack_topic(payload)
                    if cmd == CMD_PUB_RETAIN:
                        with self._lock:
                            if body:
                                self._retained[topic] = body
                            else:
                                # MQTT semantics: empty retained publish
                                # deletes the retained entry
                                self._retained.pop(topic, None)
                    self._fanout(topic, body)
                elif cmd == CMD_BYE:
                    break
        except (P.QueryProtocolError, OSError):
            pass
        finally:
            with self._lock:
                self._subs = [(t, s) for t, s in self._subs if s is not conn]
                self._wlocks.pop(conn, None)
            try:
                conn.close()
            except OSError:
                pass

    def _send_locked(self, conn: socket.socket, payload: bytes) -> None:
        with self._lock:
            wlock = self._wlocks.setdefault(conn, threading.Lock())
        with wlock:
            _send(conn, CMD_MSG, payload)

    def _fanout(self, topic: str, body: bytes):
        with self._lock:
            targets = [s for t, s in self._subs if _topic_matches(t, topic)]
        dead = []
        payload = _pack_topic(topic, body)
        for s in targets:
            try:
                self._send_locked(s, payload)
            except OSError:
                dead.append(s)
        if dead:
            with self._lock:
                self._subs = [(t, s) for t, s in self._subs
                              if s not in dead]
                for s in dead:
                    self._wlocks.pop(s, None)


def parse_broker_spec(spec: Optional[str], host: str = "127.0.0.1",
                      port: int = 1883) -> Tuple[str, str, int]:
    """THE broker-spelling parser (one source of truth for the pubsub
    elements' ``broker`` property and discovery's ``broker_host``):
    ``shim``/``native``/empty → in-process shim at (host, port);
    ``mqtt`` → real MQTT at (host, port); ``mqtt://h[:p]`` → real MQTT
    with the URL overriding host/port."""
    s = (spec or "shim").strip()
    if s in ("", "shim", "native"):
        return "shim", host, port
    if s == "mqtt":
        return "mqtt", host, port
    if s.startswith("mqtt://"):
        rest = s[len("mqtt://"):]
        if rest:
            h, _, p = rest.partition(":")
            return "mqtt", h or host, int(p) if p else port
        return "mqtt", host, port
    raise ValueError(f"pubsub: unknown broker {spec!r} (shim|mqtt[://h:p])")


class Client:
    """Pub/sub client: publish + callback-based subscribe."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 timeout: float = 10.0):
        self.sock = P.connect(host, port, timeout=timeout)
        self.sock.settimeout(None)
        self._cbs: List[Tuple[str, Callable[[str, bytes], None]]] = []
        self._lock = threading.Lock()
        self._rx: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: set when the receive loop died unexpectedly (broker gone /
        #: corrupt frame) — consumers can poll this instead of hanging
        self.failed = threading.Event()

    def publish(self, topic: str, payload: bytes,
                retain: bool = False) -> None:
        with self._lock:
            _send(self.sock, CMD_PUB_RETAIN if retain else CMD_PUB,
                  _pack_topic(topic, payload))

    def subscribe(self, topic: str,
                  callback: Callable[[str, bytes], None]) -> None:
        self._cbs.append((topic, callback))
        with self._lock:
            _send(self.sock, CMD_SUB, _pack_topic(topic, b""))
        if self._rx is None:
            self._rx = threading.Thread(target=self._rx_loop,
                                        name="pubsub-rx", daemon=True)
            self._rx.start()

    def _rx_loop(self):
        try:
            while not self._stop.is_set():
                cmd, payload = _recv(self.sock)
                if cmd != CMD_MSG:
                    continue
                topic, body = _unpack_topic(payload)
                for pattern, cb in self._cbs:
                    if _topic_matches(pattern, topic):
                        try:
                            cb(topic, body)
                        except Exception as e:  # noqa: BLE001
                            log.warning("subscriber callback error: %s", e)
        except (P.QueryProtocolError, OSError) as e:
            if not self._stop.is_set():
                log.warning("pubsub receive loop lost broker: %s", e)
                self.failed.set()

    def close(self) -> None:
        self._stop.set()
        try:
            with self._lock:
                _send(self.sock, CMD_BYE, b"")
        except OSError:
            pass
        self.sock.close()


# ---------------------------------------------------------------------------
# cross-host timestamp rebasing (reference ntputil.c + mqttsink base-time
# header fields, mqttcommon.h:49-63)
# ---------------------------------------------------------------------------
def epoch_ns() -> int:
    return time.time_ns()


#: envelope magic+version: peers with a different envelope layout fail
#: loudly instead of misparsing timestamps as payload
_ENVELOPE_MAGIC = b"NPE2"


def make_buffer_envelope(buf_payload: bytes, pts: Optional[int],
                         base_epoch: Optional[int] = None,
                         sent_epoch: Optional[int] = None) -> bytes:
    """Prefix sender base-epoch + send-epoch + pts so receivers can rebase
    timestamps by base-epoch difference (the reference's
    _put_timestamp_on_gst_buf math, mqttsrc.c:1381-1404 — latency-free,
    unlike a first-message arrival delta)."""
    return _ENVELOPE_MAGIC + struct.pack(
        "<qqq",
        epoch_ns() if base_epoch is None else base_epoch,
        epoch_ns() if sent_epoch is None else sent_epoch,
        -1 if pts is None else pts,
    ) + buf_payload


def parse_buffer_envelope(data: bytes) -> Tuple[int, int, Optional[int],
                                                bytes]:
    if data[:4] != _ENVELOPE_MAGIC:
        raise ValueError(
            "pubsub: buffer envelope magic/version mismatch (peer runs an "
            "incompatible framework version)")
    base_epoch, sent_epoch, pts = struct.unpack_from("<qqq", data, 4)
    return base_epoch, sent_epoch, (None if pts < 0 else pts), data[28:]
