"""Query server core — accept loop, per-client queues, result routing.

Reference: ``tensor_query_server.c`` (262 LoC) + the server halves of
``tensor_query_common.c``: listen, handshake caps, queue received buffers
(tagged with client id), and send results back to the right client
(serversink routes by the GstMetaQuery client-id, tensor_meta.c).
"""

from __future__ import annotations

import os
import queue as _queue
import socket
import threading
from typing import Dict, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("query.server")


class QueryServer:
    """Accepts query clients; exposes a queue of (client_id, buffer).

    Transport backends, in preference order:

    - **native** — the C++ epoll core (``native/nnstpu_server.cc``): one
      native thread owns all sockets, handshake/framing/reassembly run
      GIL-free, Python only unpacks complete buffers. The reference's
      server is native C for the same reason (tensor_query_common.c).
    - **pure-Python** — thread-per-client fallback, always available;
      forced with ``NNSTPU_PURE_PY_SERVER=1`` (also what CI uses to keep
      the fallback honest).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 3000,
                 caps_str: str = "", max_queue: int = 64,
                 wire: str = "nnstpu", sink_port: int = 0):
        self.host = host
        self.port = port
        self.caps_str = caps_str
        self.max_queue = max_queue
        #: "nnstpu" = NTQ1 framing (self-describing tensors); "nnstreamer"
        #: = the reference's raw-struct wire (query/refwire.py) on TWO
        #: ports (src=port, sink=sink_port) so reference edge peers can
        #: offload to us unmodified
        self.wire = wire
        self.sink_port = sink_port
        self.incoming: _queue.Queue = _queue.Queue(maxsize=max_queue)
        self._clients: Dict[int, socket.socket] = {}
        self._clients_lock = threading.Lock()
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._core = None  # NativeServerCore when the native path is live
        self._sink_core = None  # refwire: native sink-port core
        self._refwire = None    # refwire: pure-Python two-port server
        self._config = None     # refwire: TensorsConfig for reconstruction
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        self._m_requests = reg.counter(
            "nns_query_requests_total",
            "Buffers received from query clients", wire=self.wire)
        self._m_errors = reg.counter(
            "nns_query_errors_total",
            "Malformed / undeliverable query frames", wire=self.wire)
        if caps_str and wire == "nnstreamer":
            try:
                from nnstreamer_tpu.pipeline.parse import parse_caps_string
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self._config = TensorsConfig.from_caps(
                    parse_caps_string(caps_str))
            except Exception as e:  # noqa: BLE001 — caps stay advisory
                log.info("refwire caps %r not parseable (%s); "
                         "mems surface as u8", caps_str, e)

    @property
    def native(self) -> bool:
        return self._core is not None

    def start(self) -> "QueryServer":
        self._stop.clear()
        if self.wire == "nnstreamer":
            return self._start_refwire()
        if not os.environ.get("NNSTPU_PURE_PY_SERVER"):
            try:
                from nnstreamer_tpu.native import NativeServerCore

                self._core = NativeServerCore(
                    self.host, self.port, self.caps_str, self.max_queue)
                self.port = self._core.port
                return self
            except OSError as e:
                log.info("native server core unavailable (%s); "
                         "using pure-Python transport", e)
                self._core = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]  # resolve port 0
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="query-server-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def _start_refwire(self) -> "QueryServer":
        """Reference-wire transport: native epoll cores when available
        (wire mode 1 = src port, 2 = sink port), else the pure-Python
        two-port server (query/refwire.py)."""
        if not os.environ.get("NNSTPU_PURE_PY_SERVER"):
            try:
                from nnstreamer_tpu.native import NativeServerCore

                self._core = NativeServerCore(
                    self.host, self.port, self.caps_str, self.max_queue,
                    wire=1)
                try:
                    self._sink_core = NativeServerCore(
                        self.host, self.sink_port, "", self.max_queue,
                        wire=2)
                except OSError:
                    self._core.stop()
                    self._core = None
                    raise
                self.port = self._core.port
                self.sink_port = self._sink_core.port
                return self
            except OSError as e:
                log.info("native refwire cores unavailable (%s); "
                         "using pure-Python transport", e)
                self._core = self._sink_core = None
        from nnstreamer_tpu.query.refwire import RefWireQueryServer

        self._refwire = RefWireQueryServer(
            host=self.host, src_port=self.port, sink_port=self.sink_port,
            caps_str=self.caps_str, max_queue=self.max_queue).start()
        self.port = self._refwire.src_port
        self.sink_port = self._refwire.sink_port
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._refwire is not None:
            self._refwire.stop()
            self._refwire = None
            return
        if self._sink_core is not None:
            self._sink_core.stop()
            self._sink_core = None
        if self._core is not None:
            self._core.stop()
            self._core = None
            return
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        with self._clients_lock:
            for sock in self._clients.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._clients.clear()
        try:  # unblock a consumer waiting in get_buffer (native parity)
            self.incoming.put_nowait(None)
        except _queue.Full:
            pass  # consumer isn't blocked on an empty queue

    # -- accept/receive ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._clients_lock:
                client_id = self._next_id
                self._next_id += 1
                self._clients[client_id] = conn
            threading.Thread(
                target=self._client_loop, args=(client_id, conn),
                name=f"query-client-{client_id}", daemon=True
            ).start()
            log.info("client %d connected from %s", client_id, addr)

    def _client_loop(self, client_id: int, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = P.recv_msg(conn)
                if cmd is P.Cmd.REQUEST_INFO:
                    # caps negotiation: client caps in payload; approve and
                    # return our caps + assigned client id
                    P.send_msg(conn, P.Cmd.APPROVE, self.caps_str.encode())
                    P.send_msg(conn, P.Cmd.CLIENT_ID,
                               str(client_id).encode())
                elif cmd is P.Cmd.TRANSFER:
                    try:
                        buf = P.unpack_buffer(payload)
                    except Exception as e:  # noqa: BLE001 — corrupt frame:
                        # orderly disconnect (matches the native path's
                        # kick-on-bad-frame), not a thread-killing traceback
                        self._m_errors.inc()
                        log.warning("bad frame from client %d (%s); "
                                    "disconnecting it", client_id, e)
                        break
                    buf.meta["query_client_id"] = client_id
                    self.incoming.put(buf)
                elif cmd is P.Cmd.PING:
                    P.send_msg(conn, P.Cmd.PING)
                elif cmd is P.Cmd.BYE:
                    break
        except (P.QueryProtocolError, OSError) as e:
            log.info("client %d disconnected: %s", client_id, e)
        finally:
            with self._clients_lock:
                self._clients.pop(client_id, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- reference-wire reconstruction --------------------------------------
    def _refwire_buf(self, client_id: int, info: dict,
                     mems) -> Optional[TensorBuffer]:
        """None on a mem/caps mismatch — the serving loop must survive
        one client's malformed buffer (drop the frame, not the
        pipeline)."""
        from nnstreamer_tpu.query import refwire as R

        try:
            if self._config is not None:
                buf = R.mems_to_buffer(mems, self._config, info)
            else:
                import numpy as np

                buf = TensorBuffer(
                    [np.frombuffer(m, dtype=np.uint8) for m in mems],
                    pts=info.get("pts"), dts=info.get("dts"),
                    duration=info.get("duration"))
        except ValueError as e:
            self._m_errors.inc()
            log.warning("refwire buffer from client %d does not match "
                        "the configured caps (%s); dropping it",
                        client_id, e)
            return None
        buf.meta["query_client_id"] = client_id
        return buf

    # -- results -------------------------------------------------------------
    def send_result(self, client_id: int, buf: TensorBuffer) -> bool:
        if self.wire == "nnstreamer":
            from nnstreamer_tpu.query import refwire as R

            mems = R.buffer_to_mems(buf.to_host())
            refsrv = self._refwire
            if refsrv is not None:
                return refsrv.send_result(client_id, mems, pts=buf.pts)
            sink_core = self._sink_core
            if sink_core is None:
                return False
            raw = R.pack_buffer_frames(mems, pts=buf.pts)
            ok = sink_core.send_raw(client_id, raw)
            if not ok:
                self._m_errors.inc()
                log.warning("refwire result for client %d not deliverable",
                            client_id)
            return ok
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            ok = core.send(client_id, int(P.Cmd.RESULT),
                           P.pack_buffer(buf))
            if not ok:
                self._m_errors.inc()
                log.warning("result for client %d not deliverable",
                            client_id)
            return ok
        with self._clients_lock:
            conn = self._clients.get(client_id)
        if conn is None:
            self._m_errors.inc()
            log.warning("result for unknown client %d dropped", client_id)
            return False
        try:
            P.send_buffer(conn, buf, cmd=P.Cmd.RESULT)
            return True
        except OSError as e:
            self._m_errors.inc()
            log.warning("send to client %d failed: %s", client_id, e)
            return False

    def get_buffer(self, timeout: Optional[float] = None
                   ) -> Optional[TensorBuffer]:
        buf = self._get_buffer_impl(timeout)
        if buf is not None:
            self._m_requests.inc()
        return buf

    def _get_buffer_impl(self, timeout: Optional[float] = None
                         ) -> Optional[TensorBuffer]:
        if self.wire == "nnstreamer":
            from nnstreamer_tpu.query import refwire as R

            refsrv = self._refwire
            if refsrv is not None:
                got = refsrv.get(timeout=timeout)
                if got is None:
                    return None
                cid, info, mems = got
                return self._refwire_buf(cid, info, mems)
            core = self._core
            if core is None:
                return None
            got = core.wait_pop(timeout)
            if got is None:
                return None
            cid, payload = got
            try:
                info, mems = R.split_assembled(payload)
            except R.RefWireError as e:
                self._m_errors.inc()
                log.warning("bad refwire frame from client %d (%s); "
                            "disconnecting it", cid, e)
                core.kick(cid)
                return None
            return self._refwire_buf(cid, info, mems)
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            import time as _time

            deadline = None if timeout is None \
                else _time.monotonic() + timeout
            while True:
                if deadline is None:
                    remaining = None  # block-forever parity with Queue.get
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None
                got = core.wait_pop(remaining)
                if got is None:
                    return None
                client_id, payload = got
                try:
                    buf = P.unpack_buffer(payload)
                except Exception as e:  # noqa: BLE001 — corrupt frame:
                    # disconnect the sender (pure-Python parity: its client
                    # loop dies on a bad frame) and keep waiting
                    self._m_errors.inc()
                    log.warning("bad frame from client %d (%s); "
                                "disconnecting it", client_id, e)
                    core.kick(client_id)
                    continue
                buf.meta["query_client_id"] = client_id
                return buf
        try:
            return self.incoming.get(timeout=timeout)
        except _queue.Empty:
            return None
