"""Query server core — accept loop, per-client queues, result routing.

Reference: ``tensor_query_server.c`` (262 LoC) + the server halves of
``tensor_query_common.c``: listen, handshake caps, queue received buffers
(tagged with client id), and send results back to the right client
(serversink routes by the GstMetaQuery client-id, tensor_meta.c).
"""

from __future__ import annotations

import os
import queue as _queue
import socket
import threading
from typing import Dict, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("query.server")


class QueryServer:
    """Accepts query clients; exposes a queue of (client_id, buffer).

    Transport backends, in preference order:

    - **native** — the C++ epoll core (``native/nnstpu_server.cc``): one
      native thread owns all sockets, handshake/framing/reassembly run
      GIL-free, Python only unpacks complete buffers. The reference's
      server is native C for the same reason (tensor_query_common.c).
    - **pure-Python** — thread-per-client fallback, always available;
      forced with ``NNSTPU_PURE_PY_SERVER=1`` (also what CI uses to keep
      the fallback honest).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 3000,
                 caps_str: str = "", max_queue: int = 64):
        self.host = host
        self.port = port
        self.caps_str = caps_str
        self.max_queue = max_queue
        self.incoming: _queue.Queue = _queue.Queue(maxsize=max_queue)
        self._clients: Dict[int, socket.socket] = {}
        self._clients_lock = threading.Lock()
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._core = None  # NativeServerCore when the native path is live

    @property
    def native(self) -> bool:
        return self._core is not None

    def start(self) -> "QueryServer":
        self._stop.clear()
        if not os.environ.get("NNSTPU_PURE_PY_SERVER"):
            try:
                from nnstreamer_tpu.native import NativeServerCore

                self._core = NativeServerCore(
                    self.host, self.port, self.caps_str, self.max_queue)
                self.port = self._core.port
                return self
            except OSError as e:
                log.info("native server core unavailable (%s); "
                         "using pure-Python transport", e)
                self._core = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]  # resolve port 0
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="query-server-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._core is not None:
            self._core.stop()
            self._core = None
            return
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        with self._clients_lock:
            for sock in self._clients.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._clients.clear()
        try:  # unblock a consumer waiting in get_buffer (native parity)
            self.incoming.put_nowait(None)
        except _queue.Full:
            pass  # consumer isn't blocked on an empty queue

    # -- accept/receive ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._clients_lock:
                client_id = self._next_id
                self._next_id += 1
                self._clients[client_id] = conn
            threading.Thread(
                target=self._client_loop, args=(client_id, conn),
                name=f"query-client-{client_id}", daemon=True
            ).start()
            log.info("client %d connected from %s", client_id, addr)

    def _client_loop(self, client_id: int, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = P.recv_msg(conn)
                if cmd is P.Cmd.REQUEST_INFO:
                    # caps negotiation: client caps in payload; approve and
                    # return our caps + assigned client id
                    P.send_msg(conn, P.Cmd.APPROVE, self.caps_str.encode())
                    P.send_msg(conn, P.Cmd.CLIENT_ID,
                               str(client_id).encode())
                elif cmd is P.Cmd.TRANSFER:
                    try:
                        buf = P.unpack_buffer(payload)
                    except Exception as e:  # noqa: BLE001 — corrupt frame:
                        # orderly disconnect (matches the native path's
                        # kick-on-bad-frame), not a thread-killing traceback
                        log.warning("bad frame from client %d (%s); "
                                    "disconnecting it", client_id, e)
                        break
                    buf.meta["query_client_id"] = client_id
                    self.incoming.put(buf)
                elif cmd is P.Cmd.PING:
                    P.send_msg(conn, P.Cmd.PING)
                elif cmd is P.Cmd.BYE:
                    break
        except (P.QueryProtocolError, OSError) as e:
            log.info("client %d disconnected: %s", client_id, e)
        finally:
            with self._clients_lock:
                self._clients.pop(client_id, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- results -------------------------------------------------------------
    def send_result(self, client_id: int, buf: TensorBuffer) -> bool:
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            ok = core.send(client_id, int(P.Cmd.RESULT),
                           P.pack_buffer(buf))
            if not ok:
                log.warning("result for client %d not deliverable",
                            client_id)
            return ok
        with self._clients_lock:
            conn = self._clients.get(client_id)
        if conn is None:
            log.warning("result for unknown client %d dropped", client_id)
            return False
        try:
            P.send_buffer(conn, buf, cmd=P.Cmd.RESULT)
            return True
        except OSError as e:
            log.warning("send to client %d failed: %s", client_id, e)
            return False

    def get_buffer(self, timeout: Optional[float] = None
                   ) -> Optional[TensorBuffer]:
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            import time as _time

            deadline = None if timeout is None \
                else _time.monotonic() + timeout
            while True:
                if deadline is None:
                    remaining = None  # block-forever parity with Queue.get
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None
                got = core.wait_pop(remaining)
                if got is None:
                    return None
                client_id, payload = got
                try:
                    buf = P.unpack_buffer(payload)
                except Exception as e:  # noqa: BLE001 — corrupt frame:
                    # disconnect the sender (pure-Python parity: its client
                    # loop dies on a bad frame) and keep waiting
                    log.warning("bad frame from client %d (%s); "
                                "disconnecting it", client_id, e)
                    core.kick(client_id)
                    continue
                buf.meta["query_client_id"] = client_id
                return buf
        try:
            return self.incoming.get(timeout=timeout)
        except _queue.Empty:
            return None
