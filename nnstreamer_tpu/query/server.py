"""Query server core — accept loop, per-client queues, result routing.

Reference: ``tensor_query_server.c`` (262 LoC) + the server halves of
``tensor_query_common.c``: listen, handshake caps, queue received buffers
(tagged with client id), and send results back to the right client
(serversink routes by the GstMetaQuery client-id, tensor_meta.c).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("query.server")


class QueryServer:
    """Accepts query clients; exposes a queue of (client_id, buffer)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 3000,
                 caps_str: str = "", max_queue: int = 64):
        self.host = host
        self.port = port
        self.caps_str = caps_str
        self.incoming: _queue.Queue = _queue.Queue(maxsize=max_queue)
        self._clients: Dict[int, socket.socket] = {}
        self._clients_lock = threading.Lock()
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> "QueryServer":
        self._stop.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]  # resolve port 0
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="query-server-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        with self._clients_lock:
            for sock in self._clients.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._clients.clear()

    # -- accept/receive ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._clients_lock:
                client_id = self._next_id
                self._next_id += 1
                self._clients[client_id] = conn
            threading.Thread(
                target=self._client_loop, args=(client_id, conn),
                name=f"query-client-{client_id}", daemon=True
            ).start()
            log.info("client %d connected from %s", client_id, addr)

    def _client_loop(self, client_id: int, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = P.recv_msg(conn)
                if cmd is P.Cmd.REQUEST_INFO:
                    # caps negotiation: client caps in payload; approve and
                    # return our caps + assigned client id
                    P.send_msg(conn, P.Cmd.APPROVE, self.caps_str.encode())
                    P.send_msg(conn, P.Cmd.CLIENT_ID,
                               str(client_id).encode())
                elif cmd is P.Cmd.TRANSFER:
                    buf = P.unpack_buffer(payload)
                    buf.meta["query_client_id"] = client_id
                    self.incoming.put(buf)
                elif cmd is P.Cmd.PING:
                    P.send_msg(conn, P.Cmd.PING)
                elif cmd is P.Cmd.BYE:
                    break
        except (P.QueryProtocolError, OSError) as e:
            log.info("client %d disconnected: %s", client_id, e)
        finally:
            with self._clients_lock:
                self._clients.pop(client_id, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- results -------------------------------------------------------------
    def send_result(self, client_id: int, buf: TensorBuffer) -> bool:
        with self._clients_lock:
            conn = self._clients.get(client_id)
        if conn is None:
            log.warning("result for unknown client %d dropped", client_id)
            return False
        try:
            P.send_buffer(conn, buf, cmd=P.Cmd.RESULT)
            return True
        except OSError as e:
            log.warning("send to client %d failed: %s", client_id, e)
            return False

    def get_buffer(self, timeout: Optional[float] = None
                   ) -> Optional[TensorBuffer]:
        try:
            return self.incoming.get(timeout=timeout)
        except _queue.Empty:
            return None
