"""Query server core — accept loop, per-client queues, result routing.

Reference: ``tensor_query_server.c`` (262 LoC) + the server halves of
``tensor_query_common.c``: listen, handshake caps, queue received buffers
(tagged with client id), and send results back to the right client
(serversink routes by the GstMetaQuery client-id, tensor_meta.c).
"""

from __future__ import annotations

import os
import queue as _queue
import socket
import struct
import sys
import threading
import time
from typing import Any, Dict, Optional, Tuple

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.query import resilience as _res
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("query.server")


class QueryServer:
    """Accepts query clients; exposes a queue of (client_id, buffer).

    Transport backends, in preference order:

    - **native** — the C++ epoll core (``native/nnstpu_server.cc``): one
      native thread owns all sockets, handshake/framing/reassembly run
      GIL-free, Python only unpacks complete buffers. The reference's
      server is native C for the same reason (tensor_query_common.c).
    - **pure-Python** — thread-per-client fallback, always available;
      forced with ``NNSTPU_PURE_PY_SERVER=1`` (also what CI uses to keep
      the fallback honest).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 3000,
                 caps_str: str = "", max_queue: int = 64,
                 wire: str = "nnstpu", sink_port: int = 0,
                 resilient: bool = False):
        self.host = host
        self.port = port
        self.caps_str = caps_str
        self.max_queue = max_queue
        #: resilient mode: serve the extended protocol (HELLO /
        #: TRANSFER_EX dedup, deadline propagation, EXPIRED notices) on
        #: the pure-Python transport — the native epoll core doesn't
        #: speak the extended commands, so it is bypassed when set
        self.resilient = bool(resilient)
        #: "nnstpu" = NTQ1 framing (self-describing tensors); "nnstreamer"
        #: = the reference's raw-struct wire (query/refwire.py) on TWO
        #: ports (src=port, sink=sink_port) so reference edge peers can
        #: offload to us unmodified
        self.wire = wire
        self.sink_port = sink_port
        self.incoming: _queue.Queue = _queue.Queue(maxsize=max_queue)
        self._clients: Dict[int, socket.socket] = {}
        self._clients_lock = threading.Lock()
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._core = None  # NativeServerCore when the native path is live
        self._sink_core = None  # refwire: native sink-port core
        self._refwire = None    # refwire: pure-Python two-port server
        self._config = None     # refwire: TensorsConfig for reconstruction
        # resilient-protocol state, all keyed by the HELLO-announced
        # client *instance* (stable across that client's reconnects)
        self._dedup: Dict[str, _res.DedupWindow] = {}
        self._instances: Dict[str, int] = {}      # instance → live client id
        self._conn_instance: Dict[int, str] = {}  # client id → instance
        #: instances that negotiated the dt1 distributed-trace feature
        #: in their HELLO (obs/distributed) — only these ever see EX2
        self._dt1_instances: set = set()
        self._endpoint_name: Optional[str] = None
        #: chaos-test witnesses: duplicate requests absorbed / frames
        #: expired remotely (mirrors of the nns_net_* counters)
        self.dedup_hits = 0
        self.remote_expired = 0
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        self._m_requests = reg.counter(
            "nns_query_requests_total",
            "Buffers received from query clients", wire=self.wire)
        self._m_errors = reg.counter(
            "nns_query_errors_total",
            "Malformed / undeliverable query frames", wire=self.wire)
        if caps_str and wire == "nnstreamer":
            try:
                from nnstreamer_tpu.pipeline.parse import parse_caps_string
                from nnstreamer_tpu.tensors.types import TensorsConfig

                self._config = TensorsConfig.from_caps(
                    parse_caps_string(caps_str))
            except Exception as e:  # noqa: BLE001 — caps stay advisory
                log.info("refwire caps %r not parseable (%s); "
                         "mems surface as u8", caps_str, e)

    @property
    def native(self) -> bool:
        return self._core is not None

    def start(self) -> "QueryServer":
        self._stop.clear()
        if self.wire == "nnstreamer":
            return self._start_refwire()
        if self.resilient:
            log.info("resilient mode: using the pure-Python transport "
                     "(the native core does not speak the extended "
                     "protocol)")
        elif not os.environ.get("NNSTPU_PURE_PY_SERVER"):
            try:
                from nnstreamer_tpu.native import NativeServerCore

                self._core = NativeServerCore(
                    self.host, self.port, self.caps_str, self.max_queue)
                self.port = self._core.port
                return self
            except OSError as e:
                log.info("native server core unavailable (%s); "
                         "using pure-Python transport", e)
                self._core = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]  # resolve port 0
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="query-server-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def _start_refwire(self) -> "QueryServer":
        """Reference-wire transport: native epoll cores when available
        (wire mode 1 = src port, 2 = sink port), else the pure-Python
        two-port server (query/refwire.py)."""
        if not os.environ.get("NNSTPU_PURE_PY_SERVER"):
            try:
                from nnstreamer_tpu.native import NativeServerCore

                self._core = NativeServerCore(
                    self.host, self.port, self.caps_str, self.max_queue,
                    wire=1)
                try:
                    self._sink_core = NativeServerCore(
                        self.host, self.sink_port, "", self.max_queue,
                        wire=2)
                except OSError:
                    self._core.stop()
                    self._core = None
                    raise
                self.port = self._core.port
                self.sink_port = self._sink_core.port
                return self
            except OSError as e:
                log.info("native refwire cores unavailable (%s); "
                         "using pure-Python transport", e)
                self._core = self._sink_core = None
        from nnstreamer_tpu.query.refwire import RefWireQueryServer

        self._refwire = RefWireQueryServer(
            host=self.host, src_port=self.port, sink_port=self.sink_port,
            caps_str=self.caps_str, max_queue=self.max_queue).start()
        self.port = self._refwire.src_port
        self.sink_port = self._refwire.sink_port
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._refwire is not None:
            self._refwire.stop()
            self._refwire = None
            return
        if self._sink_core is not None:
            self._sink_core.stop()
            self._sink_core = None
        if self._core is not None:
            self._core.stop()
            self._core = None
            return
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        with self._clients_lock:
            for sock in self._clients.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._clients.clear()
        try:  # unblock a consumer waiting in get_buffer (native parity)
            self.incoming.put_nowait(None)
        except _queue.Full:
            pass  # consumer isn't blocked on an empty queue

    # -- accept/receive ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.resilient and sys.platform.startswith(
                    ("linux", "darwin")):
                # bounded SENDS without touching recv (same trick as
                # query/mqtt.py): EXPIRED notices and replayed results go
                # out from scheduler/sink threads — a half-open client
                # whose window closed must fail the send, not wedge them
                tv = struct.pack("ll", 5, 0)
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
            with self._clients_lock:
                client_id = self._next_id
                self._next_id += 1
                self._clients[client_id] = conn
            threading.Thread(
                target=self._client_loop, args=(client_id, conn),
                name=f"query-client-{client_id}", daemon=True
            ).start()
            log.info("client %d connected from %s", client_id, addr)

    def _client_loop(self, client_id: int, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = P.recv_msg(conn)
                if cmd is P.Cmd.REQUEST_INFO:
                    # caps negotiation: client caps in payload; approve and
                    # return our caps + assigned client id
                    P.send_msg(conn, P.Cmd.APPROVE, self.caps_str.encode())
                    P.send_msg(conn, P.Cmd.CLIENT_ID,
                               str(client_id).encode())
                elif cmd is P.Cmd.TRANSFER:
                    try:
                        buf = P.unpack_buffer(payload)
                    except Exception as e:  # noqa: BLE001 — corrupt frame:
                        # orderly disconnect (matches the native path's
                        # kick-on-bad-frame), not a thread-killing traceback
                        self._m_errors.inc()
                        log.warning("bad frame from client %d (%s); "
                                    "disconnecting it", client_id, e)
                        break
                    buf.meta["query_client_id"] = client_id
                    self.incoming.put(buf)
                elif cmd is P.Cmd.HELLO:
                    self._handle_hello(client_id, conn, payload)
                elif cmd is P.Cmd.TRANSFER_EX:
                    if not self._handle_transfer_ex(client_id, conn,
                                                    payload):
                        break
                elif cmd is P.Cmd.TRANSFER_EX2:
                    if not self._handle_transfer_ex(client_id, conn,
                                                    payload, ext2=True):
                        break
                elif cmd is P.Cmd.PING:
                    P.send_msg(conn, P.Cmd.PING)
                elif cmd is P.Cmd.BYE:
                    break
        except (P.QueryProtocolError, OSError) as e:
            log.info("client %d disconnected: %s", client_id, e)
        finally:
            with self._clients_lock:
                self._clients.pop(client_id, None)
                instance = self._conn_instance.pop(client_id, None)
                # the instance mapping survives only until the client's
                # NEXT connection claims it (reconnect routing); clear it
                # if it still points at this dead connection
                if instance is not None and \
                        self._instances.get(instance) == client_id:
                    self._instances.pop(instance, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- resilient protocol (HELLO / TRANSFER_EX / EXPIRED) ------------------
    def _handle_hello(self, client_id: int, conn: socket.socket,
                      payload: bytes) -> None:
        """HELLO announces the client's stable instance identity and its
        dedup-window size; the reply acknowledges extended-protocol
        support (a classic server would silently ignore the command, so
        the client treats a missing echo as 'speak classic'). A trailing
        feature token list (``instance:window:dt1``) negotiates the
        distributed-trace extension: the echo grants only what this
        server also speaks, so a mixed-version fleet degrades per
        connection instead of breaking."""
        from nnstreamer_tpu.obs import distributed as _dist

        instance, _, rest = payload.decode().partition(":")
        win, _, feats = rest.partition(":")
        try:
            window = max(1, int(win)) if win else 64
        except ValueError:
            window = 64
        dt1 = _dist.FEATURE in _dist.parse_features(feats) \
            and _dist.enabled()
        with self._clients_lock:
            self._conn_instance[client_id] = instance
            self._instances[instance] = client_id
            if instance not in self._dedup:
                self._dedup[instance] = _res.DedupWindow(size=window)
            if dt1:
                self._dt1_instances.add(instance)
            else:
                self._dt1_instances.discard(instance)
        P.send_msg(conn, P.Cmd.HELLO,
                   b"ok:" + _dist.FEATURE.encode() if dt1 else b"ok")
        log.info("client %d is resilient instance %s (dedup window %d%s)",
                 client_id, instance[:12], window,
                 ", dist-trace" if dt1 else "")

    def _handle_transfer_ex(self, client_id: int, conn: socket.socket,
                            payload: bytes, ext2: bool = False) -> bool:
        """One extended transfer: dedup first (a resend of a resolved
        request replays the cached reply, a still-pending one is
        dropped), then the deadline gate, then normal ingress. With
        ``ext2`` the header also carries distributed-trace context
        (trace id + client send stamp) that rides the buffer meta to
        result egress. Returns False to disconnect the client (bad
        frame)."""
        trace_id = 0
        try:
            if ext2:
                req_id, slack_s, trace_id, _sent_wall, _blob, body = \
                    P.unpack_ext2(payload)
            else:
                req_id, slack_s, body = P.unpack_ext(payload)
        except P.QueryProtocolError as e:
            self._m_errors.inc()
            log.warning("bad extended frame from client %d (%s); "
                        "disconnecting it", client_id, e)
            return False
        with self._clients_lock:
            instance = self._conn_instance.get(client_id)
            dedup = self._dedup.get(instance) if instance else None
        if dedup is None:
            self._m_errors.inc()
            log.warning("TRANSFER_EX from client %d before HELLO; "
                        "disconnecting it", client_id)
            return False
        verdict = dedup.admit(req_id)
        if verdict is _res.PENDING:
            # original invocation still in flight — its reply will route
            # to this instance's current connection when it lands
            self.dedup_hits += 1
            _res.metrics()["dedup_hits"].inc()
            return True
        if verdict is not _res.NEW:
            # already resolved: replay the cached reply, don't re-invoke
            self.dedup_hits += 1
            _res.metrics()["dedup_hits"].inc()
            cached_cmd, cached_payload = verdict
            P.send_msg(conn, cached_cmd, cached_payload)
            return True
        now = time.monotonic()
        if slack_s == 0.0:
            # the sender clamps an already-blown deadline to exactly 0:
            # expired on arrival — shed before paying for unpack/invoke
            self._expire_req(instance, req_id, conn=conn)
            return True
        try:
            buf = P.unpack_buffer(body)
        except Exception as e:  # noqa: BLE001 — corrupt frame: orderly
            # disconnect, same as the classic TRANSFER path. Forget the
            # dedup admit so the client's resend of the intact frame
            # invokes instead of being dropped as a duplicate
            dedup.forget(req_id)
            self._m_errors.inc()
            log.warning("bad frame from client %d (%s); disconnecting it",
                        client_id, e)
            return False
        buf.meta["query_client_id"] = client_id
        buf.meta["net_req_id"] = req_id
        buf.meta["net_instance"] = instance
        if ext2:
            from nnstreamer_tpu.obs import distributed as _dist

            # remote trace segment opens here: the ingress stamp is the
            # anchor result egress measures remote_total against, and
            # the wall stamp is the advisory send/recv split hint the
            # client clamps inside its own RTT window
            buf.meta["dist_trace"] = {
                "trace_id": trace_id,
                "recv_t": now,
                "recv_wall": _dist.wall_now(),
            }
        if slack_s > 0.0:
            # propagated deadline: stamp the remaining budget so the SLO
            # scheduler's admission test (serving/scheduler.py decide())
            # sees the sender's clock, and leave a shed hook so
            # note_shed can notify the origin client
            buf.meta["deadline_t"] = now + slack_s
            buf.meta["_net_expire"] = (self, instance, req_id)
        self.incoming.put(buf)
        return True

    def _expire_req(self, instance: str, req_id: int,
                    conn: Optional[socket.socket] = None) -> None:
        """Record + send an EXPIRED notice; the reply is cached in the
        dedup window so a resend of the expired request replays the
        notice instead of re-entering the pipeline."""
        reply = (P.Cmd.EXPIRED, P.pack_ext(req_id, -1.0))
        with self._clients_lock:
            dedup = self._dedup.get(instance)
        if dedup is not None:
            dedup.resolve(req_id, reply)
        self.remote_expired += 1
        _res.metrics()["expired_remote"].inc()
        if conn is None:
            with self._clients_lock:
                cid = self._instances.get(instance)
                conn = self._clients.get(cid) if cid is not None else None
        if conn is None:
            return
        try:
            P.send_msg(conn, *reply)
        except OSError as e:
            log.info("EXPIRED notice for req %d not deliverable: %s",
                     req_id, e)

    def send_expired(self, instance: str, req_id: int) -> None:
        """Scheduler-shed hook (``resilience.note_remote_shed``): the
        remote SLO scheduler dropped this frame before dispatch."""
        self._expire_req(instance, req_id)

    # -- serving continuity --------------------------------------------------
    def checkpoint_state(self) -> Dict[str, Any]:
        """Durable resilient-protocol state for a rolling restart: the
        per-instance dedup windows (resolved replies only — they are
        plain command/bytes tuples) plus the chaos-test witness
        counters. Connection maps are NOT included: sockets die with
        the process, and each client's reconnect HELLO re-binds its
        instance to the new connection, landing resends in its restored
        window."""
        with self._clients_lock:
            windows = dict(self._dedup)
        return {
            "dedup": {inst: w.snapshot() for inst, w in windows.items()},
            "dedup_hits": self.dedup_hits,
            "remote_expired": self.remote_expired,
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        for inst, wstate in (state.get("dedup") or {}).items():
            with self._clients_lock:
                w = self._dedup.get(inst)
                if w is None:
                    # default-sized window: restore() below adopts the
                    # saved size, keeping this method's key reads
                    # symmetric with checkpoint_state (NNS115)
                    w = self._dedup[inst] = _res.DedupWindow()
            w.restore(wstate)
        self.dedup_hits += int(state.get("dedup_hits", 0))
        self.remote_expired += int(state.get("remote_expired", 0))

    # -- reference-wire reconstruction --------------------------------------
    def _refwire_buf(self, client_id: int, info: dict,
                     mems) -> Optional[TensorBuffer]:
        """None on a mem/caps mismatch — the serving loop must survive
        one client's malformed buffer (drop the frame, not the
        pipeline)."""
        from nnstreamer_tpu.query import refwire as R

        try:
            if self._config is not None:
                buf = R.mems_to_buffer(mems, self._config, info)
            else:
                import numpy as np

                buf = TensorBuffer(
                    [np.frombuffer(m, dtype=np.uint8) for m in mems],
                    pts=info.get("pts"), dts=info.get("dts"),
                    duration=info.get("duration"))
        except ValueError as e:
            self._m_errors.inc()
            log.warning("refwire buffer from client %d does not match "
                        "the configured caps (%s); dropping it",
                        client_id, e)
            return None
        buf.meta["query_client_id"] = client_id
        return buf

    # -- results -------------------------------------------------------------
    def send_result(self, client_id: int, buf: TensorBuffer) -> bool:
        if self.wire == "nnstreamer":
            from nnstreamer_tpu.query import refwire as R

            mems = R.buffer_to_mems(buf.to_host())
            refsrv = self._refwire
            if refsrv is not None:
                return refsrv.send_result(client_id, mems, pts=buf.pts)
            sink_core = self._sink_core
            if sink_core is None:
                return False
            raw = R.pack_buffer_frames(mems, pts=buf.pts)
            ok = sink_core.send_raw(client_id, raw)
            if not ok:
                self._m_errors.inc()
                log.warning("refwire result for client %d not deliverable",
                            client_id)
            return ok
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            ok = core.send(client_id, int(P.Cmd.RESULT),
                           P.pack_buffer(buf))
            if not ok:
                self._m_errors.inc()
                log.warning("result for client %d not deliverable",
                            client_id)
            return ok
        req_id = buf.meta.get("net_req_id")
        if req_id is not None:
            return self._send_result_ex(client_id, buf, int(req_id))
        with self._clients_lock:
            conn = self._clients.get(client_id)
        if conn is None:
            self._m_errors.inc()
            log.warning("result for unknown client %d dropped", client_id)
            return False
        try:
            P.send_buffer(conn, buf, cmd=P.Cmd.RESULT)
            return True
        except OSError as e:
            self._m_errors.inc()
            log.warning("send to client %d failed: %s", client_id, e)
            return False

    def _endpoint(self) -> str:
        """Stable human-readable name for this server in remote spans."""
        if self._endpoint_name is None:
            host = self.host if self.host not in ("", "0.0.0.0") \
                else socket.gethostname()
            self._endpoint_name = f"{host}:{self.port}"
        return self._endpoint_name

    def _send_result_ex(self, client_id: int, buf: TensorBuffer,
                        req_id: int) -> bool:
        """Resilient result: cache the reply in the instance's dedup
        window (so a post-reconnect resend replays it), then send it to
        the instance's CURRENT connection — which, after a flap, is a
        different client id than the one the request arrived on."""
        instance = buf.meta.get("net_instance")
        with self._clients_lock:
            dedup = self._dedup.get(instance) if instance else None
            cid = self._instances.get(instance, client_id) \
                if instance else client_id
            conn = self._clients.get(cid)
            dt1 = instance in self._dt1_instances if instance else False
        dist = buf.meta.get("dist_trace")
        if dt1 and isinstance(dist, dict):
            # close the remote trace segment: piggyback this frame's
            # span vector (durations only — skew-safe) on the result
            from nnstreamer_tpu.obs import distributed as _dist
            from nnstreamer_tpu.obs import timeline as _tl

            now = time.monotonic()
            total = max(now - float(dist.get("recv_t", now)), 0.0)
            stages = _dist.collect_frame_stages(
                buf.meta.get(_tl.TRACE_SEQ_META))
            blob = _dist.pack_span_blob(
                stages, total, float(dist.get("recv_wall", 0.0)),
                _dist.wall_now(), self._endpoint())
            reply = (P.Cmd.RESULT_EX2,
                     P.pack_ext2(req_id, -1.0,
                                 int(dist.get("trace_id", 0)),
                                 float(dist.get("recv_wall", 0.0)),
                                 blob, P.pack_buffer(buf)))
        else:
            reply = (P.Cmd.RESULT_EX, P.pack_ext(req_id, -1.0,
                                                 P.pack_buffer(buf)))
        if dedup is not None:
            dedup.resolve(req_id, reply)
        if conn is None:
            # cached for replay: the client's reconnect resend gets it
            log.info("result for instance %s req %d cached (no live "
                     "connection)", str(instance)[:12], req_id)
            return False
        try:
            P.send_msg(conn, *reply)
            return True
        except OSError as e:
            self._m_errors.inc()
            log.warning("resilient result send to client %d failed: %s",
                        cid, e)
            return False

    def get_buffer(self, timeout: Optional[float] = None
                   ) -> Optional[TensorBuffer]:
        buf = self._get_buffer_impl(timeout)
        if buf is not None:
            self._m_requests.inc()
        return buf

    def _get_buffer_impl(self, timeout: Optional[float] = None
                         ) -> Optional[TensorBuffer]:
        if self.wire == "nnstreamer":
            from nnstreamer_tpu.query import refwire as R

            refsrv = self._refwire
            if refsrv is not None:
                got = refsrv.get(timeout=timeout)
                if got is None:
                    return None
                cid, info, mems = got
                return self._refwire_buf(cid, info, mems)
            core = self._core
            if core is None:
                return None
            got = core.wait_pop(timeout)
            if got is None:
                return None
            cid, payload = got
            try:
                info, mems = R.split_assembled(payload)
            except R.RefWireError as e:
                self._m_errors.inc()
                log.warning("bad refwire frame from client %d (%s); "
                            "disconnecting it", cid, e)
                core.kick(cid)
                return None
            return self._refwire_buf(cid, info, mems)
        core = self._core  # capture once: stop() nulls the attribute
        if core is not None:
            import time as _time

            deadline = None if timeout is None \
                else _time.monotonic() + timeout
            while True:
                if deadline is None:
                    remaining = None  # block-forever parity with Queue.get
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None
                got = core.wait_pop(remaining)
                if got is None:
                    return None
                client_id, payload = got
                try:
                    buf = P.unpack_buffer(payload)
                except Exception as e:  # noqa: BLE001 — corrupt frame:
                    # disconnect the sender (pure-Python parity: its client
                    # loop dies on a bad frame) and keep waiting
                    self._m_errors.inc()
                    log.warning("bad frame from client %d (%s); "
                                "disconnecting it", client_id, e)
                    core.kick(client_id)
                    continue
                buf.meta["query_client_id"] = client_id
                return buf
        try:
            return self.incoming.get(timeout=timeout)
        except _queue.Empty:
            return None
