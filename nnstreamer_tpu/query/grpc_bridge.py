"""gRPC TensorService — the DCN-facing streaming bridge.

Reference: ``ext/nnstreamer/extra/nnstreamer_grpc_*`` (NNStreamerRPC class,
nnstreamer_grpc_common.h:32) exposing ``TensorService`` from
``ext/nnstreamer/include/nnstreamer.proto:43-49``:

    service TensorService {
      rpc SendTensors (stream Tensors) returns (Empty);   // client→server
      rpc RecvTensors (Empty) returns (stream Tensors);   // server→client
    }

Same service shape here, built on grpcio generic handlers with the
framework's own wire codecs as (de)serializers — the ``idl`` option
picks protobuf / flexbuf / flatbuf (all reference-layout, interoperable
with a reference nnstreamer peer, rank-4 normalizing, no pts on the
wire) or ``nnstpu-flex`` (framework-native framing: carries pts,
allows rank>4 and fp16/bf16, but only our peers parse it); no
generated stubs. In the TPU deployment this
is the DCN ingress/egress: frames arrive over gRPC, flow device-resident
through the pipeline, and results stream back; intra-slice movement is
XLA collectives, never this path (SURVEY §5 distributed-backend mapping).
"""

from __future__ import annotations

import queue as _queue
import threading
from concurrent import futures
from typing import Callable, Iterator, Optional

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.pipeline import faults as _faults
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("grpc")

SERVICE = "nnstreamer.protobuf.TensorService"


def _codecs(idl: str):
    """(encode: TensorBuffer→bytes, decode: bytes→TensorBuffer) per IDL."""
    if idl == "protobuf":
        from nnstreamer_tpu.decoders.protobuf_codec import (
            decode_protobuf,
            encode_protobuf,
        )

        return encode_protobuf, decode_protobuf
    if idl == "flexbuf":
        # reference FlexBuffers layout — interoperates with a reference
        # nnstreamer gRPC peer (tensor_decoder/tensordec-flexbuf.cc map)
        from nnstreamer_tpu.decoders.flexbuf import (
            decode_flexbuf,
            encode_flexbuf,
        )

        return encode_flexbuf, decode_flexbuf
    if idl == "nnstpu-flex":
        # framework-native framing: carries pts, allows rank>4/fp16
        from nnstreamer_tpu.decoders.flexbuf import decode_flex, encode_flex

        return encode_flex, decode_flex
    if idl == "flatbuf":
        from nnstreamer_tpu.decoders.flatbuf_codec import (
            decode_flatbuf,
            encode_flatbuf,
        )

        return encode_flatbuf, decode_flatbuf
    raise ValueError(
        f"grpc: unknown idl {idl!r} (protobuf|flexbuf|flatbuf|nnstpu-flex)")


def _noop_serializer(_) -> bytes:  # Empty message
    return b""


def _noop_deserializer(raw: bytes) -> bytes:
    # grpcio interprets a None deserializer result as a failure, so the
    # Empty message round-trips as the empty byte string
    return raw or b""


class TensorServiceServer:
    """Hosts TensorService; hands received buffers to ``on_recv`` and
    streams buffers from an internal queue to RecvTensors callers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 idl: str = "protobuf",
                 on_recv: Optional[Callable[[TensorBuffer], None]] = None):
        import grpc

        self._encode, self._decode = _codecs(idl)
        self.on_recv = on_recv
        # bounded with drop-oldest: a server with no (or a slow)
        # RecvTensors subscriber must not grow without bound at video rate
        self._sendq: _queue.Queue = _queue.Queue(maxsize=64)
        self._stop = threading.Event()
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        self._m_recv = reg.counter(
            "nns_grpc_requests_total",
            "Buffers moved through TensorService",
            method="SendTensors", idl=idl)
        self._m_send = reg.counter(
            "nns_grpc_requests_total",
            "Buffers moved through TensorService",
            method="RecvTensors", idl=idl)
        self._m_errors = reg.counter(
            "nns_grpc_errors_total",
            "on_recv callback failures", idl=idl)
        self._m_send_drops = reg.counter(
            "nns_grpc_send_drops_total",
            "RecvTensors-queue buffers displaced by backpressure", idl=idl)

        def send_tensors(request_iterator, context):
            # client→server stream; requests arrive already decoded.
            # Cross-hop trace context rides the gRPC invocation metadata
            # (the codecs carry no meta dict) — stamp it onto every
            # buffer so the receiving pipeline's ledger sees the hop.
            trace_md = {k: v for k, v in (context.invocation_metadata()
                                          or ())
                        if k in ("nns-trace-id", "nns-sent-wall")}
            for buf in request_iterator:
                self._m_recv.inc()
                if trace_md:
                    from nnstreamer_tpu.obs import distributed as _dist

                    try:
                        buf.meta[_dist.TRACE_ID_META] = \
                            int(trace_md.get("nns-trace-id", 0))
                        buf.meta[_dist.SENT_WALL_META] = \
                            float(trace_md.get("nns-sent-wall", 0.0))
                    except (TypeError, ValueError):
                        pass
                if self.on_recv is not None:
                    try:
                        self.on_recv(buf)
                    except Exception:  # noqa: BLE001 — one bad frame must
                        # not tear down the client's whole send stream
                        self._m_errors.inc()
                        log.exception("on_recv callback failed")
            return b""  # Empty

        def recv_tensors(request, context):
            # server→client stream from the send queue
            while not self._stop.is_set():
                try:
                    item = self._sendq.get(timeout=0.1)
                except _queue.Empty:
                    continue
                if item is None:
                    return
                yield item

        handler = grpc.method_handlers_generic_handler(SERVICE, {
            "SendTensors": grpc.stream_unary_rpc_method_handler(
                send_tensors,
                request_deserializer=self._decode,
                response_serializer=_noop_serializer,
            ),
            "RecvTensors": grpc.unary_stream_rpc_method_handler(
                recv_tensors,
                request_deserializer=_noop_deserializer,
                response_serializer=self._encode,
            ),
        })
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RuntimeError(f"grpc: cannot bind {host}:{port}")

    def start(self):
        self._server.start()
        log.info("TensorService listening on :%d", self.port)
        return self

    def send(self, buf: TensorBuffer) -> None:
        """Queue a buffer for RecvTensors streams (drops oldest on
        backpressure, like a leaky downstream queue)."""
        self._m_send.inc()
        while True:
            try:
                self._sendq.put_nowait(buf)
                return
            except _queue.Full:
                try:
                    self._sendq.get_nowait()
                    self._m_send_drops.inc()
                except _queue.Empty:
                    pass

    def stop(self, grace: float = 1.0):
        self._stop.set()
        self._sendq.put(None)
        self._server.stop(grace)


class TensorServiceClient:
    """Client side: stream buffers up (SendTensors) or down (RecvTensors)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 idl: str = "protobuf"):
        import grpc

        self._encode, self._decode = _codecs(idl)
        self.target = f"{host}:{port}"
        self._closed = False
        self._channel = grpc.insecure_channel(self.target)
        self._send_rpc = self._channel.stream_unary(
            f"/{SERVICE}/SendTensors",
            request_serializer=self._encode,
            response_deserializer=_noop_deserializer,
        )
        self._recv_rpc = self._channel.unary_stream(
            f"/{SERVICE}/RecvTensors",
            request_serializer=_noop_serializer,
            response_deserializer=self._decode,
        )

    def wait_ready(self, timeout: float = 10.0):
        import grpc

        grpc.channel_ready_future(self._channel).result(timeout=timeout)
        return self

    @staticmethod
    def _fault_hook() -> None:
        fi = _faults.ACTIVE
        if fi is not None and fi.action("grpc.call") is not None:
            # any transport verdict at this site surfaces as the same
            # error a dead channel would raise; the caller's retry path
            # (not this bridge) owns recovery
            raise ConnectionError("injected fault: grpc.call")

    def send_stream(self, buffers: Iterator[TensorBuffer],
                    timeout: Optional[float] = None) -> None:
        """Stream buffers to the server (blocks until the server acks).
        When distributed tracing is armed the stream carries trace
        context as invocation metadata (per stream — the codecs have no
        per-frame meta channel)."""
        self._fault_hook()
        metadata = None
        from nnstreamer_tpu.obs import distributed as _dist

        if _dist.enabled():
            ctx = _dist.attach_trace_meta({})
            metadata = (
                ("nns-trace-id", str(ctx[_dist.TRACE_ID_META])),
                ("nns-sent-wall", repr(ctx[_dist.SENT_WALL_META])),
            )
        self._send_rpc(iter(buffers), timeout=timeout, metadata=metadata)

    def recv_stream(self, timeout: Optional[float] = None
                    ) -> Iterator[TensorBuffer]:
        """Iterate buffers streamed by the server."""
        self._fault_hook()
        return self._recv_rpc(None, timeout=timeout)

    def close(self) -> None:
        """Idempotent channel shutdown — element ``stop()`` owns the
        call (a ``__del__`` here would race interpreter teardown and
        mask grpc's own cleanup ordering)."""
        if self._closed:
            return
        self._closed = True
        self._channel.close()

    def __enter__(self) -> "TensorServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
