"""tensor_query wire protocol — framed tensors over TCP.

Reference: ``gst/nnstreamer/tensor_query/tensor_query_common.c`` (1107 LoC):
a custom framed TCP protocol with commands REQUEST_INFO / RESPOND_APPROVE /
RESPOND_DENY / TRANSFER_START / TRANSFER_DATA / TRANSFER_END / CLIENT_ID
(tensor_query_common.h:46-56), caps-string exchange for negotiation, and
per-buffer DataInfo (pts/dts/num_mems/sizes, :57-71).

Our framing (little-endian):
  u32 magic 'NTQ1'  u32 command  u64 payload_len  payload…

Buffer payloads serialize as: i64 pts, i64 dts, i64 duration (−1 = unset),
u32 num_tensors, then per-tensor TensorMetaInfo header + raw bytes (the
flex-header framing from ``tensors.meta``). Caps exchange sends the caps
repr string; APPROVE echoes the server's src caps.
"""

from __future__ import annotations

import dataclasses
import enum
import socket
import struct
from typing import List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.meta import pack_tensor, unpack_tensor

_MAGIC = 0x4E545131  # 'NTQ1'
_HDR = struct.Struct("<IIQ")
_BUF_HDR = struct.Struct("<qqqI")

DEFAULT_TIMEOUT = 10.0  # reference QUERY_DEFAULT_TIMEOUT (tensor_query_common.h:30)


class Cmd(enum.IntEnum):
    REQUEST_INFO = 1
    APPROVE = 2
    DENY = 3
    TRANSFER = 4   # one whole buffer (start+data+end collapsed into a frame)
    RESULT = 5
    CLIENT_ID = 6
    PING = 7
    BYE = 8
    # -- resilient extension (query/resilience.py) — a client that never
    # sets a resilience knob never emits these, so the classic wire
    # (commands 1-8) stays byte-identical to pre-extension builds
    HELLO = 9        # "<instance>:<dedup window>"; server echoes HELLO
    TRANSFER_EX = 10  # ext header (req_id, slack_s) + classic buffer
    RESULT_EX = 11    # ext header (req_id, -1) + classic buffer
    EXPIRED = 12      # ext header only: deadline missed, frame shed
    # -- distributed-trace extension (obs/distributed.py) — only spoken
    # after BOTH sides advertised the "dt1" feature in the HELLO
    # exchange, so a pre-16 peer (or NNSTPU_DIST_TRACE=0) keeps every
    # wire byte identical to the resilient protocol above
    TRANSFER_EX2 = 13  # ext2 header + trace blob + classic buffer
    RESULT_EX2 = 14    # ext2 header + remote span blob + classic buffer


#: extended-command header: u64 request id + f64 deadline slack in
#: seconds (negative = no deadline; 0.0 = already expired at send time)
_EXT_HDR = struct.Struct("<Qd")

#: distributed-trace header: the _EXT_HDR pair plus a u64 trace/frame id
#: (the client Timeline's frame seq, globally qualified by instance) and
#: a f64 wall-clock stamp (epoch seconds: client send time on
#: TRANSFER_EX2, remote receive time on RESULT_EX2). Wall stamps are
#: *advisory* — the splice only ever uses them to split wire time inside
#: the client's observed RTT window, never as absolute anchors.
_EXT2_HDR = struct.Struct("<QdQd")

#: length prefix for the variable trace blob that follows _EXT2_HDR
_BLOB_LEN = struct.Struct("<I")


def pack_ext(req_id: int, slack_s: float, body: bytes = b"") -> bytes:
    return _EXT_HDR.pack(req_id, slack_s) + body


def unpack_ext(payload: bytes) -> Tuple[int, float, bytes]:
    if len(payload) < _EXT_HDR.size:
        raise QueryProtocolError("short extended header")
    req_id, slack_s = _EXT_HDR.unpack_from(payload)
    return req_id, slack_s, payload[_EXT_HDR.size:]


def pack_ext2(req_id: int, slack_s: float, trace_id: int, stamp: float,
              blob: bytes = b"", body: bytes = b"") -> bytes:
    return (_EXT2_HDR.pack(req_id, slack_s, trace_id, stamp)
            + _BLOB_LEN.pack(len(blob)) + blob + body)


def unpack_ext2(payload: bytes
                ) -> Tuple[int, float, int, float, bytes, bytes]:
    if len(payload) < _EXT2_HDR.size + _BLOB_LEN.size:
        raise QueryProtocolError("short extended-trace header")
    req_id, slack_s, trace_id, stamp = _EXT2_HDR.unpack_from(payload)
    off = _EXT2_HDR.size
    (blob_len,) = _BLOB_LEN.unpack_from(payload, off)
    off += _BLOB_LEN.size
    if len(payload) < off + blob_len:
        raise QueryProtocolError("short trace blob")
    blob = payload[off:off + blob_len]
    return req_id, slack_s, trace_id, stamp, blob, payload[off + blob_len:]


class QueryProtocolError(RuntimeError):
    pass


def send_msg(sock: socket.socket, cmd: Cmd, payload: bytes = b"") -> None:
    from nnstreamer_tpu import native

    native.send_frame(sock, _MAGIC, int(cmd), payload)  # writev, GIL-free
    # (falls back to sock.sendall internally when the .so is absent)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise QueryProtocolError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Tuple[Cmd, bytes]:
    from nnstreamer_tpu import native

    lib = native.get_lib()
    if lib is not None and sock.gettimeout() is None:
        import ctypes

        hdr = bytearray(16)
        rc = lib.nnstpu_recv_header(
            sock.fileno(), (ctypes.c_char * 16).from_buffer(hdr))
        if rc != 0:
            raise QueryProtocolError("connection closed mid-frame")
        magic, cmd, plen = _HDR.unpack(bytes(hdr))
        if magic != _MAGIC:
            raise QueryProtocolError(f"bad magic {magic:#x}")
        payload = bytearray(plen)
        if plen:
            rc = lib.nnstpu_recv_payload(
                sock.fileno(),
                (ctypes.c_char * plen).from_buffer(payload), plen)
            if rc != 0:
                raise QueryProtocolError("connection closed mid-frame")
        return Cmd(cmd), bytes(payload)
    hdr = _recv_exact(sock, _HDR.size)
    magic, cmd, plen = _HDR.unpack(hdr)
    if magic != _MAGIC:
        raise QueryProtocolError(f"bad magic {magic:#x}")
    payload = _recv_exact(sock, plen) if plen else b""
    return Cmd(cmd), payload


# -- buffer (de)serialization ----------------------------------------------
def pack_buffer(buf: TensorBuffer) -> bytes:
    host = buf.to_host()
    parts = [_BUF_HDR.pack(
        -1 if buf.pts is None else buf.pts,
        -1 if buf.dts is None else buf.dts,
        -1 if buf.duration is None else buf.duration,
        host.num_tensors,
    )]
    for t in host.tensors:
        parts.append(pack_tensor(t))
    return b"".join(parts)


def unpack_buffer(payload: bytes) -> TensorBuffer:
    pts, dts, dur, n = _BUF_HDR.unpack_from(payload)
    offset = _BUF_HDR.size
    tensors = []
    for _ in range(n):
        arr, offset = unpack_tensor(payload, offset)
        tensors.append(arr)
    return TensorBuffer(
        tensors,
        pts=None if pts < 0 else pts,
        dts=None if dts < 0 else dts,
        duration=None if dur < 0 else dur,
    )


def send_buffer(sock: socket.socket, buf: TensorBuffer,
                cmd: Cmd = Cmd.TRANSFER) -> None:
    send_msg(sock, cmd, pack_buffer(buf))


def connect(host: str, port: int, timeout: float = DEFAULT_TIMEOUT
            ) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
