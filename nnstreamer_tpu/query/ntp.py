"""SNTP client — cross-host clock correction for distributed streams.

Reference: ``gst/mqtt/ntputil.c`` (ntputil_get_epoch) does one UDP
exchange with an NTP server and takes the server transmit timestamp as
the epoch — which bakes the response's one-way latency into the result.
Here the full SNTP offset formula is used instead::

    offset = ((t1 - t0) + (t2 - t3)) / 2

with t0/t3 the client's send/receive instants and t1/t2 the server's
receive/transmit ones, so symmetric network delay cancels and the
corrected epoch excludes message latency (the exact weakness of
first-message-delta rebasing).

``corrected_epoch_ns`` caches the measured offset: one UDP round at
first use, pure ``time_ns()`` arithmetic afterwards.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Iterable, Optional, Tuple

from nnstreamer_tpu.log import get_logger

log = get_logger("ntp")

#: seconds between the NTP epoch (1900) and the Unix epoch (1970)
NTP_UNIX_DELTA = 2_208_988_800
_FRAC = 1 << 32

#: reference default (ntputil.c NTPUTIL_DEFAULT_HNAME / port 123)
DEFAULT_SERVERS: Tuple[Tuple[str, int], ...] = (("pool.ntp.org", 123),)


def _to_ntp(unix_ns: int) -> Tuple[int, int]:
    sec, ns = divmod(unix_ns, 1_000_000_000)
    return sec + NTP_UNIX_DELTA, (ns * _FRAC) // 1_000_000_000


def _from_ntp(sec: int, frac: int) -> int:
    """NTP (sec, frac) → Unix epoch ns; 0/0 means unset."""
    if sec == 0 and frac == 0:
        return 0
    return (sec - NTP_UNIX_DELTA) * 1_000_000_000 + \
        (frac * 1_000_000_000) // _FRAC


def sntp_offset_ns(server: str = "pool.ntp.org", port: int = 123,
                   timeout: float = 2.0) -> int:
    """One SNTP round → this host's clock offset (ns) vs the server.

    A positive value means the local clock is behind. Raises OSError /
    socket.timeout when the server is unreachable.
    """
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        # LI=0 VN=4 Mode=3 (client); originate ts = our send time so the
        # server echoes it back in the originate field
        t0 = time.time_ns()
        o_sec, o_frac = _to_ntp(t0)
        req = struct.pack(">B3x11I", 0x23, *([0] * 9), o_sec, o_frac)
        sock.sendto(req, (server, port))
        data, _addr = sock.recvfrom(512)
        t3 = time.time_ns()
    finally:
        sock.close()
    if len(data) < 48:
        raise ValueError(f"ntp: short response ({len(data)}B) from {server}")
    fields = struct.unpack_from(">B3x11I", data)
    recv_sec, recv_frac = fields[8], fields[9]    # t1: server receive
    xmit_sec, xmit_frac = fields[10], fields[11]  # t2: server transmit
    t1 = _from_ntp(recv_sec, recv_frac)
    t2 = _from_ntp(xmit_sec, xmit_frac)
    if t2 == 0:
        raise ValueError(f"ntp: {server} returned no transmit timestamp")
    if t1 == 0:
        # degenerate SNTP server (like the reference's minimal exchange):
        # fall back to transmit-minus-receive-instant, latency included
        return t2 - t3
    return ((t1 - t0) + (t2 - t3)) // 2


_FAILED = object()  # sentinel: this server list was tried and unreachable

_cache_lock = threading.Lock()
#: per-server-list measured offsets — elements with different ntp-server
#: settings never poison each other's correction
_cache: dict = {}


def corrected_epoch_ns(servers: Optional[Iterable[Tuple[str, int]]] = None,
                       timeout: float = 2.0) -> int:
    """NTP-corrected Unix epoch (ns): ``time_ns() + cached offset``.

    The offset is measured once per distinct server list (reference
    ntputil loops hnames the same way); on total failure logs once and
    falls back to the uncorrected clock — the element keeps streaming,
    matching mqttsink.c's get-epoch fallback behavior.
    """
    key = tuple(servers) if servers is not None else DEFAULT_SERVERS
    with _cache_lock:
        entry = _cache.get(key)
        if entry is None:
            for host, port in key:
                try:
                    entry = sntp_offset_ns(host, port, timeout)
                    log.info("ntp: offset %+d us via %s",
                             entry // 1000, host)
                    break
                except (OSError, ValueError) as e:
                    log.warning("ntp: %s:%d unreachable (%s)", host, port, e)
            else:
                entry = _FAILED
            _cache[key] = entry
    off = 0 if entry is _FAILED else entry
    return time.time_ns() + off


def reset_offset_cache() -> None:
    """Forget measured offsets (tests / long-running re-sync)."""
    with _cache_lock:
        _cache.clear()
