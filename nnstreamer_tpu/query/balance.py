"""Join-shortest-slack endpoint selection — the fleet's front door.

A replicated serving fleet (``serving/fleet.py``) is N interchangeable
``tensor_query_server`` replicas behind one discovery operation. The
client-side balancer (``tensor_query_client balance=shortest-slack``)
scores every live, breaker-closed endpoint by its *expected completion
time* for the next frame and routes to the argmin — the endpoint whose
admitted work leaves the most slack. The score composes three signals,
freshest first:

1. the client's own in-flight count to that endpoint (updated per send,
   the only per-request-fresh signal);
2. the per-endpoint RTT EWMA from ``resilience.EndpointStats`` (updated
   per result);
3. the load block from the replica's refreshed discovery ad
   (``queue_depth`` / ``service_ms`` / ``slack_headroom_ms`` out of the
   ``SloScheduler`` snapshot, updated at the ad-refresh cadence).

Pre-fleet ads carry no ``load`` block and parse as *load-unknown*
(:func:`parse_ad_load` returns ``None``): the balancer falls back to
RTT + local in-flight alone, so a mixed fleet of old and new replicas
still balances. Everything here is a pure function of its arguments —
no sockets, no clocks — so the policy is unit-testable in isolation.

Metrics (NNS106 ``nns_lb_`` prefix):

- ``nns_lb_route_total{endpoint}`` — frames routed per endpoint
- ``nns_lb_score_ms``              — the winning score of the last route
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: balance property values (tensor_query_client)
MODE_OFF = "off"
MODE_SHORTEST_SLACK = "shortest-slack"

#: RTT assumed for an endpoint with no samples yet (seconds): below any
#: real network RTT, so a cold replica out-scores warmed-up siblings and
#: gets probed immediately (one result gives it a real EWMA), but
#: nonzero so the tie against an idle sibling still breaks on load
DEFAULT_RTT_S = 0.0005


@dataclasses.dataclass(frozen=True)
class EndpointLoad:
    """The live load block a refreshed discovery ad carries."""

    #: frames sitting in the replica's ingress queue ahead of a new send
    queue_depth: int = 0
    #: scheduler's per-frame service-time estimate (EWMA), milliseconds
    service_ms: Optional[float] = None
    #: budget minus the expected wait of a newly admitted frame,
    #: milliseconds; negative = the replica is already over budget
    slack_headroom_ms: Optional[float] = None


def parse_ad_load(info: Optional[dict]) -> Optional[EndpointLoad]:
    """Parse the ``load`` block out of a discovery-ad payload.

    ``None`` for pre-fleet ads (no ``load`` key) and for malformed
    blocks: load-unknown, the balancer scores on RTT + local in-flight
    alone — the compat contract that lets a PR-20 client balance across
    replicas still running older builds."""
    load = (info or {}).get("load")
    if not isinstance(load, dict):
        return None
    try:
        svc = load.get("service_ms")
        head = load.get("slack_headroom_ms")
        return EndpointLoad(
            queue_depth=max(0, int(load.get("queue_depth", 0))),
            service_ms=float(svc) if svc is not None else None,
            slack_headroom_ms=float(head) if head is not None else None,
        )
    except (TypeError, ValueError):
        return None


def score(rtt_s: Optional[float], inflight: int,
          load: Optional[EndpointLoad]) -> float:
    """Expected completion time (seconds) of the next frame sent to this
    endpoint — lower is better.

    ``rtt_s`` None (no samples yet) scores at :data:`DEFAULT_RTT_S`.
    With a load block, queued depth converts to time through the
    replica's own service estimate; without one (load-unknown), the
    local in-flight count converts through the RTT itself — pessimistic
    but monotone, which is all join-shortest-queue needs. A negative
    slack headroom (replica over budget) adds its full deficit, pushing
    an overloaded replica to the back of the ranking even when its RTT
    history still looks good."""
    base = DEFAULT_RTT_S if rtt_s is None else max(0.0, float(rtt_s))
    per_frame = None
    if load is not None and load.service_ms:
        per_frame = max(0.0, load.service_ms) / 1e3
    if per_frame is None or per_frame <= 0.0:
        per_frame = max(base, 1e-4)
    s = base + max(0, int(inflight)) * per_frame
    if load is not None:
        s += load.queue_depth * per_frame
        if load.slack_headroom_ms is not None and \
                load.slack_headroom_ms < 0.0:
            s += -load.slack_headroom_ms / 1e3
    return s


def rank(candidates: Sequence[Tuple[Tuple[str, int], Optional[float], int,
                                    Optional[EndpointLoad]]]
         ) -> List[Tuple[float, Tuple[str, int]]]:
    """Rank ``(endpoint, rtt_s, inflight, load)`` candidates best-first.

    Breaker-open endpoints must already be excluded by the caller (the
    breaker is stateful; this module stays pure). Ties break on the
    endpoint tuple itself — (host, port) lexicographic — so two equal
    replicas always rank in the same deterministic order."""
    scored = [(score(rtt, inflight, load), ep)
              for ep, rtt, inflight, load in candidates]
    scored.sort(key=lambda t: (t[0], t[1]))
    return scored


# --------------------------------------------------------------------------
# metrics
# --------------------------------------------------------------------------
_LB_METRICS: Optional[Dict[str, Any]] = None
_ROUTE_COUNTERS: Dict[str, Any] = {}
_METRICS_LOCK = threading.Lock()


def lb_metrics() -> Dict[str, Any]:
    """Lazy shared balancer metrics (any transport thread may route)."""
    global _LB_METRICS
    if _LB_METRICS is None:
        with _METRICS_LOCK:
            if _LB_METRICS is None:
                from nnstreamer_tpu.obs import get_registry

                reg = get_registry()
                _LB_METRICS = {
                    "score_ms": reg.gauge(
                        "nns_lb_score_ms",
                        "Winning shortest-slack score of the most "
                        "recent route (expected completion, ms)"),
                    "reroutes": reg.counter(
                        "nns_lb_reroutes_total",
                        "In-flight frames re-routed to another replica "
                        "after their endpoint exhausted reconnects"),
                }
    return _LB_METRICS


def route_counter(endpoint: str):
    """Per-endpoint ``nns_lb_route_total`` counter, cached by label."""
    c = _ROUTE_COUNTERS.get(endpoint)
    if c is None:
        with _METRICS_LOCK:
            c = _ROUTE_COUNTERS.get(endpoint)
            if c is None:
                from nnstreamer_tpu.obs import get_registry

                c = get_registry().counter(
                    "nns_lb_route_total",
                    "Frames routed to this endpoint by the "
                    "shortest-slack balancer",
                    endpoint=endpoint)
                _ROUTE_COUNTERS[endpoint] = c
    return c


def note_route(endpoint: Tuple[str, int], score_s: float) -> None:
    """Record one routing decision in the balancer metrics."""
    route_counter(f"{endpoint[0]}:{endpoint[1]}").inc()
    lb_metrics()["score_ms"].set(score_s * 1e3)
