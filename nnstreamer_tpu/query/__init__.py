"""L6 — distributed offload: query protocol/client/server, pub/sub, gRPC."""
