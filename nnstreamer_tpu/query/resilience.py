"""Transport resilience — the policy layer under the query/gRPC/MQTT hops.

Every ROADMAP scale-out item (multi-chip fan-out, multi-tenant front
end, edge-cloud split pipelines) rides a network hop, and a hop is only
as strong as its failure story. This module holds the mechanism pieces
that story is built from; the transports compose them:

- :class:`RetryPolicy` — bounded exponential backoff with deterministic
  jitter (same pure-function discipline as ``pipeline/supervise.py``'s
  ``_backoff_sleep``: the delay for (key, attempt) is reproducible, so a
  seeded chaos run replays the same recovery timeline).
- :class:`CircuitBreaker` — per-endpoint closed/open/half-open breaker.
  A dead endpoint costs one connect timeout per reset window instead of
  one per frame; a half-open probe re-closes it on the first success.
- :class:`EndpointStats` — EWMA + reservoir-p99 latency tracker. Its
  :meth:`~EndpointStats.hedge_timeout` is the p99-based hedge timer: a
  recv that outlives it fails over to the next replica instead of
  waiting out the full protocol timeout.
- :class:`DedupWindow` — server-side idempotency: a bounded per-client
  map of request-id → pending/cached-reply. Reconnect resends and
  hedged duplicates replay the cached reply; they never double-invoke.
- :class:`PendingEntry` — one in-flight request on a reliable client
  connection: enough state (packed body, deadline) to resend the
  undelivered suffix in order after a reconnect.
- :func:`note_remote_shed` — the scheduler hook: when the remote SLO
  scheduler sheds a propagated-deadline frame, the origin server sends
  the client an EXPIRED notice so the slot frees instead of timing out.

Deadline propagation itself rides the extended wire commands in
``query/protocol.py`` (``TRANSFER_EX`` carries ``(req_id, slack_s)``);
the client half lives in ``elements/query.py`` (``reliable=true``), the
server half in ``query/server.py``. Everything is off by default: no
knob set means no extended command ever crosses the wire and the
protocol bytes are identical to a build without this module.

Metrics (NNS106 ``nns_net_`` prefix):

- ``nns_net_retries_total``        — frames resent after a reconnect
- ``nns_net_hedges_total``         — hedged failovers to another replica
- ``nns_net_breaker_state``        — per-endpoint gauge (0 closed /
  1 open / 2 half-open)
- ``nns_net_dedup_hits_total``     — duplicate requests absorbed by the
  server dedup window (the zero-double-invoke witness)
- ``nns_net_deadline_expired_remote_total`` — frames the remote end
  expired (on arrival or via a scheduler shed) instead of serving late
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger

log = get_logger("resilience")

#: breaker states (the ``nns_net_breaker_state`` gauge values)
CLOSED, OPEN, HALF_OPEN = 0, 1, 2

#: hedge timer = max(configured floor, p99 * this factor) — the EWMA
#: must blow well past the tail estimate before a failover fires
HEDGE_P99_FACTOR = 1.5

#: a retry ladder must never park a streaming thread longer than this
#: per attempt (same ceiling as pipeline/supervise.py)
BACKOFF_CAP_S = 2.0


# --------------------------------------------------------------------------
# metrics
# --------------------------------------------------------------------------
_METRICS: Optional[Dict[str, Any]] = None
_BREAKER_GAUGES: Dict[str, Any] = {}
_METRICS_LOCK = threading.Lock()


def metrics() -> Dict[str, Any]:
    """Lazy shared counters (safe to call from any transport thread)."""
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                from nnstreamer_tpu.obs import get_registry

                reg = get_registry()
                _METRICS = {
                    "retries": reg.counter(
                        "nns_net_retries_total",
                        "Frames resent over a rebuilt transport "
                        "connection"),
                    "hedges": reg.counter(
                        "nns_net_hedges_total",
                        "Hedged failovers to another replica after the "
                        "hedge timer fired"),
                    "dedup_hits": reg.counter(
                        "nns_net_dedup_hits_total",
                        "Duplicate requests absorbed by the server dedup "
                        "window (replayed or dropped, never re-invoked)"),
                    "expired_remote": reg.counter(
                        "nns_net_deadline_expired_remote_total",
                        "Frames expired at the remote end (deadline "
                        "propagation: shed on arrival or by the remote "
                        "scheduler)"),
                }
    return _METRICS


def breaker_gauge(endpoint: str):
    """Per-endpoint ``nns_net_breaker_state`` gauge, cached by label."""
    g = _BREAKER_GAUGES.get(endpoint)
    if g is None:
        with _METRICS_LOCK:
            g = _BREAKER_GAUGES.get(endpoint)
            if g is None:
                from nnstreamer_tpu.obs import get_registry

                g = get_registry().gauge(
                    "nns_net_breaker_state",
                    "Circuit-breaker state per endpoint "
                    "(0 closed / 1 open / 2 half-open)",
                    endpoint=endpoint)
                _BREAKER_GAUGES[endpoint] = g
    return g


# --------------------------------------------------------------------------
# retry / backoff
# --------------------------------------------------------------------------
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``delay(attempt)`` is a pure function of ``(key, attempt)`` — a
    string-seeded RNG (sha512-hashed, PYTHONHASHSEED-independent), so a
    seeded chaos run reproduces the same recovery timeline across
    processes. ``attempt`` is 1-based.
    """

    def __init__(self, base_ms: float = 50.0, cap_s: float = BACKOFF_CAP_S,
                 key: str = ""):
        self.base_s = max(0.0, float(base_ms)) / 1e3
        self.cap_s = float(cap_s)
        self.key = key

    def delay(self, attempt: int) -> float:
        d = min(self.base_s * (2 ** (max(1, attempt) - 1)), self.cap_s)
        jitter = 0.5 + 0.5 * random.Random(
            f"{self.key}:{attempt}").random()
        return d * jitter

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        if d > 0:
            time.sleep(d)
        return d


# --------------------------------------------------------------------------
# circuit breaker
# --------------------------------------------------------------------------
class CircuitBreaker:
    """Per-endpoint closed → open → half-open breaker.

    - **closed**: all traffic allowed; ``failures`` consecutive recorded
      failures trip it open.
    - **open**: :meth:`allow` refuses until ``reset_s`` elapses, then the
      breaker moves to half-open and admits probes.
    - **half-open**: traffic allowed; the first success re-closes, the
      first failure re-opens (and restarts the reset clock).

    Thread-safe; state changes mirror into the per-endpoint
    ``nns_net_breaker_state`` gauge when ``endpoint`` is set.
    """

    def __init__(self, failures: int = 5, reset_s: float = 1.0,
                 endpoint: str = ""):
        self.failure_threshold = max(1, int(failures))
        self.reset_s = max(0.0, float(reset_s))
        self.endpoint = endpoint
        self._lock = threading.Lock()
        self._state = CLOSED
        self._fail_count = 0
        self._opened_t = 0.0
        #: state transition log (monotonic_t, state) — chaos-test witness
        self.transitions: List[Tuple[float, int]] = []

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _set_state(self, state: int, now: float) -> None:
        if state == self._state:
            return
        self._state = state
        self.transitions.append((now, state))
        if self.endpoint:
            breaker_gauge(self.endpoint).set(state)

    def allow(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state == OPEN:
                if now - self._opened_t >= self.reset_s:
                    self._set_state(HALF_OPEN, now)
                    return True
                return False
            return True

    def record_success(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._fail_count = 0
            self._set_state(CLOSED, now)

    def record_failure(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._fail_count += 1
            if self._state == HALF_OPEN or \
                    self._fail_count >= self.failure_threshold:
                self._opened_t = now
                self._set_state(OPEN, now)


# --------------------------------------------------------------------------
# endpoint latency stats / hedge timer
# --------------------------------------------------------------------------
class EndpointStats:
    """EWMA + bounded-reservoir p99 of round-trip latencies.

    The hedge timer is ``max(floor, p99 * HEDGE_P99_FACTOR)`` once at
    least :attr:`MIN_SAMPLES` observations exist; before that, the
    configured floor alone (a cold endpoint must not hedge off noise).
    """

    MIN_SAMPLES = 8

    def __init__(self, alpha: float = 0.2, window: int = 128):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._ewma: Optional[float] = None
        self._sample: Deque[float] = deque(maxlen=max(8, int(window)))

    def observe(self, rtt_s: float) -> None:
        rtt_s = max(0.0, float(rtt_s))
        with self._lock:
            self._ewma = rtt_s if self._ewma is None else \
                (1 - self.alpha) * self._ewma + self.alpha * rtt_s
            self._sample.append(rtt_s)

    def ewma(self) -> Optional[float]:
        with self._lock:
            return self._ewma

    def p99(self) -> Optional[float]:
        with self._lock:
            if len(self._sample) < self.MIN_SAMPLES:
                return None
            ordered = sorted(self._sample)
        idx = min(len(ordered) - 1, round(0.99 * (len(ordered) - 1)))
        return ordered[idx]

    def hedge_timeout(self, floor_s: float) -> float:
        p = self.p99()
        if p is None:
            return floor_s
        return max(floor_s, p * HEDGE_P99_FACTOR)


# --------------------------------------------------------------------------
# idempotent delivery
# --------------------------------------------------------------------------
#: DedupWindow.admit verdicts
NEW = "new"
PENDING = "pending"


class DedupWindow:
    """Bounded request-id → pending/cached-reply map (server side).

    One window per client *instance* (the HELLO-announced identity that
    survives reconnects), so a resend after a connection flap lands in
    the same window as the original:

    - :meth:`admit` returns :data:`NEW` for a first-seen id (marks it
      pending), :data:`PENDING` while the original invocation is still
      in flight (drop the duplicate — its reply will route to the
      instance's current connection), or the cached reply tuple for an
      already-resolved id (replay it, don't re-invoke).
    - :meth:`resolve` stores the serialized reply for future replays.

    Bounded FIFO: oldest entries fall out past ``size``. Size it to
    cover the client's in-flight window plus a reconnect burst.
    """

    def __init__(self, size: int = 64):
        self.size = max(1, int(size))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, Any]" = OrderedDict()

    def admit(self, req_id: int):
        with self._lock:
            got = self._entries.get(req_id)
            if got is None:
                self._entries[req_id] = PENDING
                while len(self._entries) > self.size:
                    self._entries.popitem(last=False)
                return NEW
            return got  # PENDING or the cached reply

    def forget(self, req_id: int) -> None:
        """Drop an admitted entry whose frame failed to parse — without
        this the id would sit at PENDING forever and the client's resend
        of the (now intact) frame would be swallowed as a duplicate."""
        with self._lock:
            self._entries.pop(req_id, None)

    def resolve(self, req_id: int, reply) -> None:
        with self._lock:
            self._entries[req_id] = reply
            self._entries.move_to_end(req_id)
            while len(self._entries) > self.size:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- serving continuity --------------------------------------------------
    def snapshot(self) -> dict:
        """Checkpointable window state: resolved replies only. PENDING
        entries are dropped — after a restart the in-flight invocation
        is gone, and the client's resend must re-invoke, which dedup
        handles exactly as a first send."""
        with self._lock:
            entries = [(rid, reply) for rid, reply in self._entries.items()
                       if reply is not PENDING]
            # size is read under the same lock restore() resizes under
            return {"size": self.size, "entries": entries}

    def restore(self, state: dict) -> None:
        with self._lock:
            self.size = max(self.size, int(state.get("size", self.size)))
            for rid, reply in state.get("entries", ()):
                self._entries[rid] = reply
                self._entries.move_to_end(rid)
            while len(self._entries) > self.size:
                self._entries.popitem(last=False)


@dataclasses.dataclass
class PendingEntry:
    """One reliable-mode request in flight: everything a reconnect needs
    to resend it (the packed classic body — slack is recomputed from
    ``deadline_t`` at each send so a resend carries the *remaining*
    budget, not the original one)."""

    req_id: int
    pts: Optional[int]
    meta: dict
    body: bytes
    deadline_t: Optional[float] = None  # monotonic; None = no deadline
    sent_t: float = 0.0
    sent_wall: float = 0.0  # advisory wall stamp for dist-trace splits
    #: (host, port) the last send went to — RTT observations credit this
    #: endpoint's EndpointStats, not whichever endpoint is current when
    #: the result lands (a hedged result may arrive after a failover)
    endpoint: Optional[Tuple[str, int]] = None

    def slack_s(self, now: float) -> float:
        """Wire slack for this send: negative = no deadline; 0.0 = the
        deadline already passed (the server expires it on arrival)."""
        if self.deadline_t is None:
            return -1.0
        return max(0.0, self.deadline_t - now)


# --------------------------------------------------------------------------
# remote-shed hook (called from SloScheduler.note_shed)
# --------------------------------------------------------------------------
def note_remote_shed(buf) -> None:
    """A remote scheduler shed a frame that arrived with a propagated
    deadline: notify the origin client with an EXPIRED notice so its
    in-flight slot frees now instead of waiting out a recv timeout.
    No-op for frames without transport meta; never raises (the shed
    path must stay non-blocking and failure-proof)."""
    hook = buf.meta.pop("_net_expire", None)
    if hook is None:
        return
    server, instance, req_id = hook
    try:
        server.send_expired(instance, req_id)
    except Exception as e:  # noqa: BLE001 — a dead client connection
        # must not break the scheduler's shed path
        log.info("expired notice for req %d not deliverable: %s",
                 req_id, e)
