"""Reference-wire tensor_query protocol (``wire=nnstreamer``).

Byte-level interop with the reference's framed-TCP query transport
(``gst/nnstreamer/tensor_query/tensor_query_common.c:320-450``): a
reference edge device can offload to our server, and our client can
offload to a reference server, with no translation layer.

Wire layout (native little-endian — the reference sends raw host
structs; ctypes oracles in ``tests/test_refwire.py`` pin every offset):

- every message starts with ``cmd``: 4-byte C enum
  (``tensor_query_common.h:46-56``):
  0 REQUEST_INFO, 1 RESPOND_APPROVE, 2 RESPOND_DENY, 3 TRANSFER_START,
  4 TRANSFER_DATA, 5 TRANSFER_END, 6 CLIENT_ID
- cmd in {REQUEST_INFO, APPROVE, DENY, TRANSFER_DATA}: ``size_t`` (u64)
  byte count, then that many raw bytes (caps strings are sent
  NUL-terminated; tensor data is raw)
- cmd in {TRANSFER_START, TRANSFER_END}: the 176-byte
  ``TensorQueryDataInfo`` struct — i64 base_time, i64 sent_time,
  u64 duration, u64 dts, u64 pts, u32 num_mems, 4 bytes of alignment
  padding, u64 mem_sizes[16] (``tensor_query_common.h:60-71``,
  NNS_TENSOR_SIZE_LIMIT=16)
- cmd CLIENT_ID: ``query_client_id_t`` = i64 (``tensor_meta.h:21``)

Conversation shape (client = ``tensor_query_client.c:377-445``):

- client → server-src port: server sends CLIENT_ID first (id =
  monotonic time in the reference; any i64 works), client sends
  REQUEST_INFO with its in-caps string, server replies APPROVE with its
  sink caps (or DENY with its src caps)
- client → server-sink port (a SECOND connection): client sends
  CLIENT_ID with the id it was assigned, then reads result buffers
- buffers (either direction): TRANSFER_START(data_info) +
  num_mems × TRANSFER_DATA + TRANSFER_END(data_info)
  (``tensor_query_common.c:976-1100``)

Unlike our ``NTQ1`` framing (query/protocol.py) the reference wire
carries NO per-tensor meta — memory chunks are raw bytes whose
shapes/dtypes come from the negotiated caps, exactly as the reference's
serversrc trusts its configured caps.
"""

from __future__ import annotations

import queue
import socket
import struct
import time
from typing import List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.query.protocol import QueryProtocolError
from nnstreamer_tpu.tensors.buffer import TensorBuffer

log = get_logger("query.refwire")

# TensorQueryCommand (tensor_query_common.h:46-56)
CMD_REQUEST_INFO = 0
CMD_RESPOND_APPROVE = 1
CMD_RESPOND_DENY = 2
CMD_TRANSFER_START = 3
CMD_TRANSFER_DATA = 4
CMD_TRANSFER_END = 5
CMD_CLIENT_ID = 6

NNS_TENSOR_SIZE_LIMIT = 16  # tensor_typedef.h:35

_CMD = struct.Struct("<i")          # C enum: 4-byte int, native endian
_SIZE = struct.Struct("<Q")         # size_t on LP64
_CLIENT_ID = struct.Struct("<q")    # query_client_id_t = int64
#: TensorQueryDataInfo: i64 base_time, i64 sent_time, u64 duration,
#: u64 dts, u64 pts, u32 num_mems, 4-byte alignment hole, u64[16]
_DATA_INFO = struct.Struct("<qqQQQI4x16Q")
DATA_INFO_SIZE = _DATA_INFO.size  # 176

#: GStreamer's GST_CLOCK_TIME_NONE — unset pts/dts on the wire
CLOCK_NONE = 0xFFFFFFFFFFFFFFFF


def _cstr(body: bytes) -> str:
    """Decode a wire C-string: bytes up to the first NUL."""
    return body.split(b"\0", 1)[0].decode(errors="replace")


class RefWireError(QueryProtocolError):
    """Wire violation — subclasses QueryProtocolError so the query
    client's retry/failover paths treat both wires uniformly."""


def pack_data_info(num_mems: int, mem_sizes: List[int],
                   pts: Optional[int] = None, dts: Optional[int] = None,
                   duration: Optional[int] = None,
                   base_time: int = 0, sent_time: int = 0) -> bytes:
    sizes = list(mem_sizes) + [0] * (NNS_TENSOR_SIZE_LIMIT - len(mem_sizes))
    return _DATA_INFO.pack(
        base_time, sent_time,
        CLOCK_NONE if duration is None else duration,
        CLOCK_NONE if dts is None else dts,
        CLOCK_NONE if pts is None else pts,
        num_mems, *sizes)


def unpack_data_info(raw: bytes) -> dict:
    vals = _DATA_INFO.unpack(raw)
    base_time, sent_time, duration, dts, pts, num_mems = vals[:6]
    return dict(
        base_time=base_time, sent_time=sent_time,
        duration=None if duration == CLOCK_NONE else duration,
        dts=None if dts == CLOCK_NONE else dts,
        pts=None if pts == CLOCK_NONE else pts,
        num_mems=num_mems, mem_sizes=list(vals[6:6 + num_mems]))


# -- socket I/O -------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        part = sock.recv(min(n, 1 << 20))
        if not part:
            raise RefWireError("peer closed mid-message")
        chunks.append(part)
        n -= len(part)
    return b"".join(chunks)


def send_cmd(sock: socket.socket, cmd: int, payload: bytes = b"") -> None:
    """Send one reference-framed message (cmd decides the body form)."""
    parts = [_CMD.pack(cmd)]
    if cmd in (CMD_REQUEST_INFO, CMD_RESPOND_APPROVE, CMD_RESPOND_DENY,
               CMD_TRANSFER_DATA):
        parts.append(_SIZE.pack(len(payload)))
        parts.append(payload)
    elif cmd in (CMD_TRANSFER_START, CMD_TRANSFER_END):
        if len(payload) != DATA_INFO_SIZE:
            raise RefWireError(
                f"data_info must be {DATA_INFO_SIZE} bytes")
        parts.append(payload)
    elif cmd == CMD_CLIENT_ID:
        if len(payload) != _CLIENT_ID.size:
            raise RefWireError("client id must be 8 bytes")
        parts.append(payload)
    else:
        raise RefWireError(f"unknown command {cmd}")
    sock.sendall(b"".join(parts))


def recv_cmd(sock: socket.socket,
             max_data: int = 1 << 33) -> Tuple[int, bytes]:
    """Receive one reference-framed message → (cmd, body bytes)."""
    (cmd,) = _CMD.unpack(_recv_exact(sock, _CMD.size))
    if cmd in (CMD_REQUEST_INFO, CMD_RESPOND_APPROVE, CMD_RESPOND_DENY,
               CMD_TRANSFER_DATA):
        (size,) = _SIZE.unpack(_recv_exact(sock, _SIZE.size))
        if size > max_data:
            raise RefWireError(f"oversized payload {size}")
        return cmd, _recv_exact(sock, int(size))
    if cmd in (CMD_TRANSFER_START, CMD_TRANSFER_END):
        return cmd, _recv_exact(sock, DATA_INFO_SIZE)
    if cmd == CMD_CLIENT_ID:
        return cmd, _recv_exact(sock, _CLIENT_ID.size)
    raise RefWireError(f"unknown command {cmd} from peer")


# -- whole-buffer transfer (tensor_query_common.c:976-1100) -----------------

def pack_buffer_frames(mems: List[bytes], pts: Optional[int] = None,
                       dts: Optional[int] = None,
                       duration: Optional[int] = None) -> bytes:
    """The complete TRANSFER_START + DATA× + END byte sequence for one
    buffer, as a single blob (sent verbatim by sockets here and by the
    native core's send_raw path)."""
    info = pack_data_info(len(mems), [len(m) for m in mems], pts=pts,
                          dts=dts, duration=duration,
                          sent_time=time.monotonic_ns() // 1000)
    parts = [_CMD.pack(CMD_TRANSFER_START), info]
    for m in mems:
        parts.append(_CMD.pack(CMD_TRANSFER_DATA))
        parts.append(_SIZE.pack(len(m)))
        parts.append(m)
    parts.append(_CMD.pack(CMD_TRANSFER_END))
    parts.append(info)
    return b"".join(parts)


def send_buffer(sock: socket.socket, mems: List[bytes],
                pts: Optional[int] = None, dts: Optional[int] = None,
                duration: Optional[int] = None) -> None:
    sock.sendall(pack_buffer_frames(mems, pts=pts, dts=dts,
                                    duration=duration))


def recv_buffer(sock: socket.socket) -> Tuple[dict, List[bytes]]:
    cmd, raw = recv_cmd(sock)
    if cmd != CMD_TRANSFER_START:
        raise RefWireError(f"expected TRANSFER_START, got {cmd}")
    info = unpack_data_info(raw)
    mems = []
    for i in range(info["num_mems"]):
        cmd, data = recv_cmd(sock)
        if cmd != CMD_TRANSFER_DATA:
            raise RefWireError(f"expected TRANSFER_DATA, got {cmd}")
        if len(data) != info["mem_sizes"][i]:
            raise RefWireError(
                f"mem {i}: announced {info['mem_sizes'][i]} bytes, "
                f"got {len(data)}")
        mems.append(data)
    cmd, _ = recv_cmd(sock)
    if cmd != CMD_TRANSFER_END:
        raise RefWireError(f"expected TRANSFER_END, got {cmd}")
    return info, mems


def split_assembled(payload: bytes) -> Tuple[dict, List[bytes]]:
    """Split the native core's assembled TRANSFER payload (DataInfo ||
    raw mems back to back — nnstpu_server.cc parse_ref_frames)."""
    if len(payload) < DATA_INFO_SIZE:
        raise RefWireError("assembled payload shorter than DataInfo")
    info = unpack_data_info(payload[:DATA_INFO_SIZE])
    mems, off = [], DATA_INFO_SIZE
    for sz in info["mem_sizes"]:
        mems.append(payload[off:off + sz])
        off += sz
    if off != len(payload):
        raise RefWireError("assembled payload size mismatch")
    return info, mems


# -- caps ↔ tensor reconstruction ------------------------------------------

def buffer_to_mems(buf: TensorBuffer) -> List[bytes]:
    """Raw per-tensor bytes (the wire carries no meta — shapes/dtypes
    ride in the negotiated caps, reference serversrc semantics)."""
    return [np.ascontiguousarray(np.asarray(t)).tobytes()
            for t in buf.tensors]


def mems_to_buffer(mems: List[bytes], config,
                   info: Optional[dict] = None) -> TensorBuffer:
    """Reassemble tensors from raw memory chunks using a negotiated
    :class:`~nnstreamer_tpu.tensors.types.TensorsConfig` (shapes/dtypes
    per caps, like the reference's serversrc trusting its caps)."""
    tensors = []
    infos = list(config.info.infos)[:len(mems)]
    for raw, ti in zip(mems, infos):
        arr = np.frombuffer(raw, dtype=ti.type.np_dtype)
        tensors.append(arr.reshape(ti.shape))
    # extra mems beyond the caps (shouldn't happen) stay raw u8
    for raw in mems[len(infos):]:
        tensors.append(np.frombuffer(raw, dtype=np.uint8))
    pts = info.get("pts") if info else None
    dts = info.get("dts") if info else None
    dur = info.get("duration") if info else None
    return TensorBuffer(tensors, pts=pts, dts=dts, duration=dur)


# -- client (tensor_query_client.c:377-445 flow) ----------------------------

class RefWireClient:
    """Offload client speaking the reference wire: two connections
    (server src + server sink ports), caps handshake, buffers out on
    src, results in on sink."""

    def __init__(self, src_host: str, src_port: int,
                 sink_host: Optional[str] = None,
                 sink_port: Optional[int] = None,
                 in_caps: str = "", timeout: float = 10.0):
        self.timeout = timeout
        self.client_id: Optional[int] = None
        self.server_caps: Optional[str] = None
        self._src = socket.create_connection((src_host, src_port),
                                             timeout=timeout)
        self._src.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            cmd, body = recv_cmd(self._src)
            if cmd != CMD_CLIENT_ID:
                raise RefWireError(f"expected CLIENT_ID first, got {cmd}")
            (self.client_id,) = _CLIENT_ID.unpack(body)
            send_cmd(self._src, CMD_REQUEST_INFO,
                     in_caps.encode() + b"\0")
            cmd, body = recv_cmd(self._src)
            if cmd == CMD_RESPOND_DENY:
                raise RefWireError(
                    f"server denied caps: {_cstr(body)}")
            if cmd != CMD_RESPOND_APPROVE:
                raise RefWireError(f"expected APPROVE, got {cmd}")
            self.server_caps = _cstr(body)
            self._sink = socket.create_connection(
                (sink_host or src_host,
                 sink_port if sink_port is not None else src_port + 1),
                timeout=timeout)
            self._sink.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                  1)
            send_cmd(self._sink, CMD_CLIENT_ID,
                     _CLIENT_ID.pack(self.client_id))
        except Exception:
            self.close()
            raise

    def send(self, mems: List[bytes], pts: Optional[int] = None) -> None:
        send_buffer(self._src, mems, pts=pts)

    def recv_result(self) -> Tuple[dict, List[bytes]]:
        return recv_buffer(self._sink)

    def close(self) -> None:
        for s in (getattr(self, "_src", None),
                  getattr(self, "_sink", None)):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


# -- server (pure-Python transport; the native epoll core handles the
#    same wire via nnstpu_server_start2 wire modes — query/server.py) ------

class RefWireQueryServer:
    """Reference-wire query server: src port (handshake + inbound
    buffers) and sink port (client-id claim + result routing), the
    two-port topology of tensor_query_serversrc/serversink."""

    def __init__(self, host: str = "0.0.0.0", src_port: int = 0,
                 sink_port: int = 0, caps_str: str = "",
                 max_queue: int = 64):
        import queue as _q
        import threading

        self.host = host
        self.caps_str = caps_str
        self.incoming: "_q.Queue" = _q.Queue(maxsize=max_queue)
        self._sinks = {}
        #: live src-port connections by client id — stop() must shut
        #: them down (close alone does not wake a blocked recv) or each
        #: client leaks a thread + ESTABLISHED socket per server cycle
        self._srcs = {}
        self._lock = threading.Lock()
        self._next_id = 1
        self._stop = threading.Event()
        self._threads = []
        self._src_listener = self._listen(host, src_port)
        self._sink_listener = self._listen(host, sink_port)
        self.src_port = self._src_listener.getsockname()[1]
        self.sink_port = self._sink_listener.getsockname()[1]

    @staticmethod
    def _listen(host, port):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(16)
        s.settimeout(0.2)
        return s

    def start(self) -> "RefWireQueryServer":
        import threading

        self._stop.clear()
        for name, fn in (("refwire-src-accept", self._src_accept),
                         ("refwire-sink-accept", self._sink_accept)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()
        for s in (self._src_listener, self._sink_listener):
            try:
                s.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._sinks.values()) + list(self._srcs.values())
            self._sinks.clear()
            self._srcs.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self.incoming.put_nowait(None)
        except queue.Full:  # consumer is not blocked on us; nothing to do
            pass

    # -- src port ----------------------------------------------------------
    def _src_accept(self):
        import threading

        while not self._stop.is_set():
            try:
                conn, addr = self._src_listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._srcs[cid] = conn
            threading.Thread(target=self._src_loop, args=(cid, conn),
                             name=f"refwire-src-{cid}",
                             daemon=True).start()
            log.info("refwire client %d connected from %s", cid, addr)

    def _caps_acceptable(self, client_caps: str) -> bool:
        """The reference's admission test (tensor_query_common.c:770-803):
        the client's announced caps must config-equal or caps-intersect
        the server's (framerate ignored — TensorsConfig.is_equal never
        compares rate). Permissive when either side is unparseable: our
        caps grammar must not reject a conformant reference peer over a
        spelling it doesn't know."""
        if not self.caps_str or not client_caps.strip():
            # no server caps to gate on / client hasn't negotiated its
            # own caps yet (our client announces "" pre-negotiation)
            return True
        try:
            from nnstreamer_tpu.pipeline.parse import parse_caps_string
            from nnstreamer_tpu.tensors.types import TensorsConfig

            server = parse_caps_string(self.caps_str)
            client = parse_caps_string(client_caps)
        except Exception:  # noqa: BLE001 — be liberal in what we accept
            return True
        try:
            if TensorsConfig.from_caps(server).is_equal(
                    TensorsConfig.from_caps(client)):
                return True
        except (ValueError, KeyError, TypeError):
            pass  # not tensor caps on one side; fall back to intersect
        return server.intersect(client) is not None

    def _src_loop(self, cid: int, conn: socket.socket):
        try:
            # reference serversrc sends the client id immediately on
            # accept (tensor_query_client.c:393-401 expects it first)
            send_cmd(conn, CMD_CLIENT_ID, _CLIENT_ID.pack(cid))
            while not self._stop.is_set():
                cmd, body = recv_cmd(conn)
                if cmd == CMD_REQUEST_INFO:
                    client_caps = _cstr(body)
                    if not self._caps_acceptable(client_caps):
                        # reference replies DENY with its own caps
                        # (tensor_query_common.c:801-803)
                        log.warning(
                            "refwire client %d caps %r rejected vs "
                            "server %r", cid, client_caps, self.caps_str)
                        send_cmd(conn, CMD_RESPOND_DENY,
                                 self.caps_str.encode() + b"\0")
                        continue
                    send_cmd(conn, CMD_RESPOND_APPROVE,
                             self.caps_str.encode() + b"\0")
                elif cmd == CMD_TRANSFER_START:
                    info = unpack_data_info(body)
                    mems = []
                    for i in range(info["num_mems"]):
                        c2, data = recv_cmd(conn)
                        if c2 != CMD_TRANSFER_DATA:
                            raise RefWireError(
                                f"expected TRANSFER_DATA, got {c2}")
                        if len(data) != info["mem_sizes"][i]:
                            raise RefWireError(
                                f"mem {i}: announced "
                                f"{info['mem_sizes'][i]} bytes, got "
                                f"{len(data)}")
                        mems.append(data)
                    c2, _ = recv_cmd(conn)
                    if c2 != CMD_TRANSFER_END:
                        raise RefWireError(
                            f"expected TRANSFER_END, got {c2}")
                    self.incoming.put((cid, info, mems))
                else:
                    raise RefWireError(f"unexpected command {cmd}")
        except (RefWireError, OSError) as e:
            log.info("refwire client %d disconnected: %s", cid, e)
        finally:
            with self._lock:
                self._srcs.pop(cid, None)
            try:
                conn.close()
            except OSError:
                pass

    # -- sink port ---------------------------------------------------------
    def _sink_accept(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._sink_listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                conn.settimeout(10.0)
                cmd, body = recv_cmd(conn)
                if cmd != CMD_CLIENT_ID:
                    raise RefWireError(
                        f"sink connection must claim CLIENT_ID, got {cmd}")
                (cid,) = _CLIENT_ID.unpack(body)
                conn.settimeout(None)
            except (RefWireError, OSError) as e:
                log.warning("refwire sink handshake failed: %s", e)
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._lock:
                old = self._sinks.pop(cid, None)
                self._sinks[cid] = conn
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass

    # -- results -----------------------------------------------------------
    def send_result(self, client_id: int, mems: List[bytes],
                    pts: Optional[int] = None) -> bool:
        with self._lock:
            conn = self._sinks.get(client_id)
        if conn is None:
            log.warning("refwire result for unknown client %d dropped",
                        client_id)
            return False
        try:
            send_buffer(conn, mems, pts=pts)
            return True
        except OSError as e:
            log.warning("refwire send to client %d failed: %s",
                        client_id, e)
            return False

    def get(self, timeout: Optional[float] = None):
        import queue as _q

        try:
            return self.incoming.get(timeout=timeout)
        except _q.Empty:
            return None
