"""SloScheduler — deadline admission, EDF ordering, and feedback-tuned
batch forming for the serving plane.

BENCH_r05's saturation p99 was ~5 s against a 50 ms budget: the only
overload defense was the leaky ingress queue's blind tail-drop, which
sheds whichever frame happens to be oldest with no notion of deadline.
This module owns the request population between ingress and device
dispatch instead:

- **Deadlines.** Every admitted frame carries ``meta["deadline_t"]`` —
  either a per-request override stamped upstream, or
  ``admitted_t + slo_budget_ms`` from the pipeline/queue budget.
- **Admission control.** A frame whose deadline cannot be met given the
  current service-rate estimate (EWMA over
  ``nns_tensor_filter_invoke_seconds`` observations plus the sink's
  completion spacing — the *slower* of the two governs, so a fused
  pipeline whose filter chain never runs is still covered) is rejected
  at the door: it never consumes queue capacity, device batches, or a
  slot in the admitted-latency population.
- **EDF ordering.** The admission queue (``pipeline/pipeline.py`` Queue
  in scheduler mode) replaces FIFO with an earliest-deadline-first heap;
  with a uniform budget deadlines are monotone in arrival order, so an
  unloaded pipeline's output is byte-identical to FIFO — the kill
  switch (budget unset) doesn't even build the scheduler.
- **Load shedding.** On overflow the queue sheds already-late frames
  first (they will miss regardless); only when nothing is late does it
  drop the least-urgent (latest-deadline) frame. The batch former also
  sheds any frame whose deadline passed while it sat in the heap —
  late work is never dispatched (serving it would burn device time on
  a guaranteed miss and then report the miss as an admitted-latency
  outlier). A shed frame's admission stamp is revoked so the admitted
  population nets out.
- **Batch forming.** The queue worker re-forms device batches from
  whatever is admitted each wake, capped by the feedback controller's
  ``batch_cap`` (kept a power of two so re-formed batches land on the
  fused region's bucketed shapes instead of forcing retraces). The
  DispatchWindow's fence provides the free-slot backpressure: a full
  window blocks the pushing worker, so batches are only formed when a
  dispatch slot frees.
- **Feedback control.** An event-driven AIMD controller (no polling
  thread, no sleeps — NNS110 enforces that for every scheduler hot
  path) steps ``batch_cap`` and the filters' ``inflight`` toward max
  admitted throughput subject to p99 ≤ ``p99_factor`` x budget, reading
  the same completion population the bench's ``latency_sat_p99_ms``
  reports. ``lanes`` is start-time-static (pipeline/lanes.py splices
  once), so the controller publishes its lane recommendation as the
  ``nns_sched_lanes_hint`` gauge for the next launch instead of lying
  about a live retune.

Exported series: ``nns_sched_admitted_total``, ``nns_sched_rejected_total``,
``nns_sched_shed_total{reason}``, ``nns_sched_deadline_slack_seconds``,
``nns_sched_batch_cap``, ``nns_sched_inflight_target``,
``nns_sched_service_time_ms``, ``nns_sched_p99_ms``,
``nns_sched_lanes_hint``. See docs/profiling.md, "SLO tuning".
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from nnstreamer_tpu.log import get_logger
from nnstreamer_tpu.obs import timeline as _timeline

log = get_logger("scheduler")


class SloRejected(RuntimeError):
    """Raised by request-path admission (serving engine) when the
    deadline is unmeetable under the current service-rate estimate."""

    def __init__(self, message: str, slack_s: float = 0.0):
        super().__init__(message)
        self.slack_s = slack_s


class ServiceRateEstimator:
    """EWMA per-frame service time from two independent witnesses.

    ``observe_invoke`` feeds backend invoke latencies (the unfused
    filter's hot path); ``observe_completion`` feeds the sink-side
    completion spacing (frames delivered per second of wall progress),
    which covers fused pipelines where the filter chain never runs and —
    unlike invoke timing — includes queueing between the dispatch and
    the materialization. Admission uses the SLOWER estimate: admitting
    on an optimistic rate re-creates exactly the late-frame pileup this
    subsystem exists to prevent."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._invoke_s: Optional[float] = None      # per-frame, EWMA
        self._drain_s: Optional[float] = None       # per-frame, EWMA
        self._last_completion_t: Optional[float] = None

    def observe_invoke(self, seconds: float, frames: int = 1) -> None:
        if seconds < 0 or frames < 1:
            return
        per = seconds / frames
        with self._lock:
            self._invoke_s = per if self._invoke_s is None else \
                (1 - self.alpha) * self._invoke_s + self.alpha * per

    def observe_completion(self, now: float, frames: int = 1) -> None:
        if frames < 1:
            return
        with self._lock:
            last = self._last_completion_t
            self._last_completion_t = now
            if last is None:
                return
            gap = now - last
            # a multi-second gap is a stall/warmup artifact, not steady
            # service; folding it in would poison admission for minutes
            if not (0.0 <= gap <= 5.0):
                return
            per = gap / frames
            self._drain_s = per if self._drain_s is None else \
                (1 - self.alpha) * self._drain_s + self.alpha * per

    def service_time_s(self) -> float:
        """Per-frame service-time estimate; 0.0 while cold (admit-all —
        rejecting on no evidence would deadlock a cold pipeline)."""
        with self._lock:
            cands = [v for v in (self._invoke_s, self._drain_s)
                     if v is not None]
        return max(cands) if cands else 0.0

    def service_fps(self) -> float:
        s = self.service_time_s()
        return (1.0 / s) if s > 0 else 0.0

    # -- serving continuity ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Checkpointable state: the two EWMAs only.
        ``_last_completion_t`` is a monotonic-clock anchor — meaningless
        in another process, it re-anchors on the first completion."""
        with self._lock:
            return {"invoke_s": self._invoke_s, "drain_s": self._drain_s}

    def restore(self, state: Dict[str, Any]) -> None:
        with self._lock:
            inv = state.get("invoke_s")
            drn = state.get("drain_s")
            self._invoke_s = float(inv) if inv is not None else None
            self._drain_s = float(drn) if drn is not None else None


class FeedbackController:
    """Event-driven AIMD over ``batch_cap`` and ``inflight``.

    Stepped from the observation path (``maybe_step`` — at most one step
    per ``interval_s``), never from a polling thread: the scheduler's
    own lint rule (NNS110) bans blocking sleeps in this subsystem.
    Policy: completion p99 above ``p99_factor`` x budget is an overload
    signal → multiplicative decrease (halve batch_cap, step inflight
    down); p99 at or under budget is headroom → additive-ish increase
    (double batch_cap toward the bucket ceiling, step inflight up).
    ``batch_cap`` stays a power of two so re-formed batches hit the
    fused region's already-traced bucketed shapes."""

    def __init__(self, budget_s: float, p99_factor: float = 2.0,
                 interval_s: float = 0.25, batch_cap: int = 8,
                 batch_cap_max: int = 64, inflight: int = 2,
                 inflight_max: int = 8, window: int = 512):
        self.budget_s = float(budget_s)
        self.p99_factor = float(p99_factor)
        self.interval_s = float(interval_s)
        self.batch_cap_max = int(batch_cap_max)
        self.inflight_max = int(inflight_max)
        self._lock = threading.Lock()
        self.batch_cap = max(1, int(batch_cap))
        self.inflight = max(1, int(inflight))
        self.steps = 0
        self.last_p99_s: Optional[float] = None
        self._last_step_t = 0.0
        self._lat: deque = deque(maxlen=int(window))

    def record_completion(self, latency_s: float) -> None:
        with self._lock:
            self._lat.append(latency_s)

    def _p99_locked(self) -> Optional[float]:
        if len(self._lat) < 8:
            return None
        vals = sorted(self._lat)
        return vals[min(len(vals) - 1, int(0.99 * (len(vals) - 1)))]

    def maybe_step(self, now: float, overload: bool = False) -> bool:
        """One AIMD step if the interval elapsed and enough completions
        accumulated. Returns True when the knobs changed. ``overload``
        forces the multiplicative-decrease branch regardless of the p99
        reading — the flight recorder's SLO burn-rate windows raise it
        while both alerting windows burn hot, which fires on a breach
        *pattern* before the windowed p99 has fully absorbed it."""
        with self._lock:
            if now - self._last_step_t < self.interval_s:
                return False
            p99 = self._p99_locked()
            if p99 is None:
                return False
            self._last_step_t = now
            self.last_p99_s = p99
            self.steps += 1
            cap0, inf0 = self.batch_cap, self.inflight
            if overload or p99 > self.p99_factor * self.budget_s:
                self.batch_cap = max(1, self.batch_cap // 2)
                self.inflight = max(1, self.inflight - 1)
            elif p99 <= self.budget_s:
                self.batch_cap = min(self.batch_cap_max, self.batch_cap * 2)
                self.inflight = min(self.inflight_max, self.inflight + 1)
            # between budget and p99_factor*budget: hold — the dead band
            # keeps the knobs from oscillating around the target
            return (self.batch_cap, self.inflight) != (cap0, inf0)

    # -- serving continuity ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Checkpointable state: AIMD knobs + the completion window.
        ``_last_step_t`` stays 0 — it is a monotonic-clock anchor, and
        restoring it would block the first post-restore step."""
        with self._lock:
            return {
                "batch_cap": self.batch_cap,
                "inflight": self.inflight,
                "steps": self.steps,
                "last_p99_s": self.last_p99_s,
                "latencies": list(self._lat),
            }

    def restore(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.batch_cap = max(1, int(state.get("batch_cap",
                                                  self.batch_cap)))
            self.inflight = max(1, int(state.get("inflight",
                                                 self.inflight)))
            self.steps = int(state.get("steps", 0))
            p99 = state.get("last_p99_s")
            self.last_p99_s = float(p99) if p99 is not None else None
            self._lat.clear()
            self._lat.extend(state.get("latencies", ()))


def token_deadline(now: float, deadline_t: float, remaining: int) -> float:
    """Per-TOKEN EDF key for continuous-batching decode: spread a
    stream's remaining slack evenly over its remaining token budget, so
    a nearly-late short stream sorts ahead of a comfortable long one —
    the serving engine feeds these into its lane selection each block
    (token-level preemption). Row-independent math: a stream's schedule
    key never depends on which other streams share the batch."""
    return now + max(0.0, deadline_t - now) / max(1, int(remaining))


class SloScheduler:
    """Owns the admitted population between ingress and device dispatch.

    Attach point: ``Pipeline.start()`` builds one per pipeline when
    ``slo_budget_ms`` is set (pipeline-level or on any queue); admission
    queues bind to it in their ``start()``. Also usable standalone by
    the serving engine (``pipeline=None``) for request-path admission.
    """

    def __init__(self, budget_ms: float, pipeline=None, name: str = "",
                 p99_factor: float = 2.0, step_interval_s: float = 0.25,
                 batch_cap: int = 8, batch_cap_max: int = 64,
                 inflight_max: int = 8):
        self.budget_ms = float(budget_ms)
        self.budget_s = self.budget_ms / 1e3
        self.pipeline = pipeline
        self.name = name or getattr(pipeline, "name", "") or "scheduler"
        self.estimator = ServiceRateEstimator()
        inflight0 = 2
        if pipeline is not None:
            for el in pipeline.elements:
                if "inflight" in el._props:
                    inflight0 = max(1, int(el.get_property("inflight")))
                    break
        self.controller = FeedbackController(
            budget_s=self.budget_s, p99_factor=p99_factor,
            interval_s=step_interval_s, batch_cap=batch_cap,
            batch_cap_max=batch_cap_max, inflight=inflight0,
            inflight_max=inflight_max)
        self._lanes_hint = self._current_lanes()
        #: serving-mesh dp fan-out (pipeline/fuse.py pipeline_shard_count,
        #: set via note_mesh at start): batch_cap() rounds down to a
        #: multiple of it so every admitted micro-batch splits evenly
        #: over the shards — a ragged batch pads (wastes) one chip-step
        #: on every device. 1 = single-device, no effect.
        self._mesh_quantum = 1
        #: decaying synthetic backlog set by the supervision layer's
        #: memory-pressure ladder (shed rung): each admission decision
        #: consumes one unit, so a pressure burst sheds at the door for
        #: a bounded run of arrivals and then self-heals
        self._mem_hold = 0
        self._obs_ready = False
        self._m: Dict[str, Any] = {}
        self._obs_init()

    # -- metrics --------------------------------------------------------------
    def _obs_init(self) -> None:
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        labels = {"pipeline": self.name}
        self._m = {
            "admitted": reg.counter(
                "nns_sched_admitted_total",
                "Frames/requests admitted under the SLO budget", **labels),
            "rejected": reg.counter(
                "nns_sched_rejected_total",
                "Frames/requests rejected at admission (deadline "
                "unmeetable under the service-rate estimate)", **labels),
            "shed_late": reg.counter(
                "nns_sched_shed_total",
                "Admitted frames shed before dispatch",
                reason="late", **labels),
            "shed_capacity": reg.counter(
                "nns_sched_shed_total",
                "Admitted frames shed before dispatch",
                reason="capacity", **labels),
            "retries": reg.counter(
                "nns_fault_sched_retry_seconds_total",
                "Wall time burnt on element retries/backoff fed into the "
                "service-rate estimate (brownout-aware admission)",
                **labels),
            "slack": reg.histogram(
                "nns_sched_deadline_slack_seconds",
                "Deadline slack at admission decision time (negative = "
                "rejected)",
                buckets=(-1.0, -0.1, -0.01, 0.0, 0.01, 0.05, 0.1,
                         0.5, 1.0, 5.0), **labels),
        }
        # weakref-bound gauge callbacks: the registry holds fns forever,
        # and a strong self would keep the whole pipeline alive with it
        import weakref

        ref = weakref.ref(self)

        def _g(attr):
            def read():
                s = ref()
                return float(attr(s)) if s is not None else 0.0
            return read

        reg.gauge("nns_sched_batch_cap",
                  "Feedback controller's current batch-forming cap",
                  fn=_g(lambda s: s.controller.batch_cap), **labels)
        reg.gauge("nns_sched_inflight_target",
                  "Feedback controller's current dispatch-window target",
                  fn=_g(lambda s: s.controller.inflight), **labels)
        reg.gauge("nns_sched_service_time_ms",
                  "EWMA per-frame service-time estimate",
                  fn=_g(lambda s: s.estimator.service_time_s() * 1e3),
                  **labels)
        reg.gauge("nns_sched_p99_ms",
                  "Controller's last observed completion p99",
                  fn=_g(lambda s: (s.controller.last_p99_s or 0.0) * 1e3),
                  **labels)
        reg.gauge("nns_sched_lanes_hint",
                  "Recommended ingest lane count for the next launch "
                  "(lanes are start-time-static)",
                  fn=_g(lambda s: s._lanes_hint), **labels)
        self._obs_ready = True

    def _current_lanes(self) -> int:
        try:
            from nnstreamer_tpu.pipeline.lanes import effective_lanes

            return effective_lanes(getattr(self.pipeline, "lanes", 1) or 1)
        except Exception:  # noqa: BLE001 — advisory gauge only
            return 1

    # -- admission ------------------------------------------------------------
    def decide(self, now: float, backlog: int,
               deadline_t: Optional[float] = None,
               budget_ms: Optional[float] = None):
        """Admission decision without side effects on a buffer:
        ``(admit, deadline_t, slack_s)``. ``backlog`` is the number of
        frames already ahead of this one (queued + undelivered); the
        estimated completion is ``now + (backlog + 1) * service_time``.
        Device-memory pressure adds a synthetic memory-backlog term
        (:meth:`_memory_backlog`) so an HBM-bound pipeline sheds at the
        door instead of OOM-ing mid-pipeline. A cold estimator
        (service_time 0) admits everything."""
        budget_s = (float(budget_ms) / 1e3 if budget_ms else self.budget_s)
        if deadline_t is None:
            deadline_t = now + budget_s
        est_done = now + \
            (max(0, backlog) + 1 + self._memory_backlog()) * \
            self.estimator.service_time_s()
        slack = deadline_t - est_done
        return slack >= 0.0, deadline_t, slack

    def _memory_backlog(self) -> int:
        """The admission-side memory-pressure term: the HBM budget
        accountant's current overage expressed in frames, plus the
        decaying hold the supervision ladder's shed rung requested. Pure
        state reads — no waits, no clock (NNS110-safe); zero with no
        accountant and no pressure (the kill-switch path is one dict
        lookup)."""
        import sys

        extra = 0
        mem = sys.modules.get("nnstreamer_tpu.tensors.memory")
        if mem is not None and mem.ACTIVE is not None:
            extra = mem.ACTIVE.admission_backlog()
        hold = self._mem_hold
        if hold > 0:
            self._mem_hold = hold - 1  # one unit per admission decision
        return extra + hold

    def note_memory_pressure(self, frames: int = 8) -> None:
        """The pressure ladder's shed rung: hold admission down for the
        next ``frames`` decisions while reclamation and retries race
        fresh arrivals for the same headroom."""
        self._mem_hold = max(self._mem_hold, int(frames))
        m = self._m.get("mem_pressure")
        if m is None:
            from nnstreamer_tpu.obs import get_registry

            m = self._m["mem_pressure"] = get_registry().counter(
                "nns_sched_mem_pressure_total",
                "Memory-pressure shed requests from the supervision "
                "ladder (admission held down while reclamation runs)",
                pipeline=self.name)
        m.inc()

    def admit(self, buf, now: float, backlog: int,
              budget_ms: Optional[float] = None) -> bool:
        """Frame-path admission: decide, record, and stamp. On admit the
        buffer carries ``admitted_t`` (the served-latency base the sink
        reads) and ``deadline_t`` (the EDF key); on reject nothing is
        stamped and the frame is the caller's to drop."""
        ok, deadline_t, slack = self.decide(
            now, backlog, deadline_t=buf.meta.get("deadline_t"),
            budget_ms=budget_ms)
        self._m["slack"].observe(slack)
        if not ok:
            self._m["rejected"].inc()
            tl = _timeline.ACTIVE
            if tl is not None:
                tl.mark("sched_reject",
                        buf.meta.get(_timeline.TRACE_SEQ_META),
                        track="scheduler",
                        slack_ms=round(slack * 1e3, 3))
            return False
        buf.meta.setdefault("admitted_t", now)
        buf.meta["deadline_t"] = deadline_t
        self._m["admitted"].inc()
        return True

    def admit_request(self, now: float, backlog: int,
                      deadline_t: Optional[float] = None) -> None:
        """Request-path admission (serving engine): raises
        :class:`SloRejected` when unmeetable, else counts the admit."""
        ok, deadline_t, slack = self.decide(now, backlog,
                                            deadline_t=deadline_t)
        self._m["slack"].observe(slack)
        if not ok:
            self._m["rejected"].inc()
            raise SloRejected(
                f"{self.name}: deadline unmeetable — backlog {backlog} x "
                f"{self.estimator.service_time_s() * 1e3:.1f} ms/frame "
                f"overruns the budget by {-slack * 1e3:.1f} ms",
                slack_s=slack)
        self._m["admitted"].inc()

    def note_shed(self, buf, now: float) -> None:
        """An admitted frame was dropped before dispatch: revoke its
        admission stamp (the admitted population must net out — a shed
        frame must never surface as a served-latency sample through a
        shared-meta path like a tee branch) and count it by reason."""
        late = buf.meta.get("deadline_t", now) <= now
        buf.meta.pop("admitted_t", None)
        buf.meta.pop("deadline_t", None)
        self._m["shed_late" if late else "shed_capacity"].inc()
        # a shed frame never reaches a dispatch fence: release its pool
        # staging stash and an exclusively-owned device payload now
        # rather than letting shed work pin HBM/slabs until GC
        from nnstreamer_tpu.pipeline.dispatch import release_shed_payload

        release_shed_payload(buf)
        if "_net_expire" in buf.meta:
            # the frame arrived over the query wire with a propagated
            # deadline: tell the origin client it was shed so its
            # in-flight slot frees now instead of timing out
            from nnstreamer_tpu.query import resilience

            resilience.note_remote_shed(buf)
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("sched_shed", buf.meta.get(_timeline.TRACE_SEQ_META),
                    track="scheduler", late=late)

    def note_shed_request(self, now: float, late: bool = True) -> None:
        """Request-path analog of :meth:`note_shed`: an ADMITTED decode
        stream had its KV blocks revoked back to the pool (serving
        engine cache-pressure shed). Replays the admission revocation
        accounting — the admitted population nets out through the same
        shed counters the frame path uses."""
        self._m["shed_late" if late else "shed_capacity"].inc()
        tl = _timeline.ACTIVE
        if tl is not None:
            tl.mark("sched_shed", None, track="scheduler", late=late)

    # -- observation feeds ----------------------------------------------------
    def observe_service(self, seconds: float, frames: int = 1) -> None:
        """Backend invoke latency (elements/filter.py hot path)."""
        self.estimator.observe_invoke(seconds, frames)

    def note_retry(self, busy_s: float) -> None:
        """An element recovered (or exhausted) a retry ladder after
        ``busy_s`` of failed attempts + backoff (pipeline/supervise.py).
        That wall time is real per-frame service cost during a brownout:
        folding it into the invoke-side estimate raises the service-time
        EWMA, so admission tightens exactly while the element is flaky
        instead of over-admitting against the healthy-path estimate."""
        if busy_s <= 0:
            return
        self._m["retries"].inc(busy_s)
        self.estimator.observe_invoke(busy_s, 1)

    def observe_completion(self, latency_s: float, now: float,
                           frames: int = 1) -> None:
        """A served frame reached the sink: feed the drain-rate estimate
        and the controller's p99 window, then give the controller its
        event-driven chance to step."""
        self.estimator.observe_completion(now, frames)
        self.controller.record_completion(latency_s)
        fr = getattr(self.pipeline, "_flight", None)
        overload = fr is not None and fr.burn_overload(now)
        if self.controller.maybe_step(now, overload=overload):
            self._apply_knobs()

    # -- knob application -----------------------------------------------------
    def note_mesh(self, shard_count: int) -> None:
        """Adopt the pipeline's serving-mesh fan-out (Pipeline.start()
        after region fusion): the admission quantum becomes the dp shard
        count so drained micro-batches always split evenly over chips."""
        self._mesh_quantum = max(1, int(shard_count))

    def batch_cap(self) -> int:
        cap = self.controller.batch_cap
        q = self._mesh_quantum
        if q > 1:
            # align DOWN to the shard quantum (but never below one full
            # mesh-wide batch): the AIMD controller keeps its power-of-
            # two ladder; only the value handed to the queue drain is
            # quantized, so controller state stays mesh-agnostic
            cap = max(q, (cap // q) * q)
        return cap

    def inflight_target(self) -> int:
        return self.controller.inflight

    def _apply_knobs(self) -> None:
        """Push the controller's inflight target onto every element that
        has the knob. Writes ``_props`` directly: ``set_property`` would
        invalidate the fused region's plan on every step, and perf_smoke
        proves the window depth changes nothing the plan depends on —
        the DispatchWindow reads the property live at each admit."""
        pipe = self.pipeline
        if pipe is None:
            return
        target = self.controller.inflight
        for el in pipe.elements:
            if "inflight" in el._props and el._props["inflight"] != target:
                el._props["inflight"] = target
        # lanes are spliced once at start(): publish the recommendation
        # instead of pretending to retune a static knob. Healthy p99 with
        # capacity sheds means ingest (not the device) is starving the
        # budget — one more lane is the next launch's cheapest lever.
        shed = (self._m["shed_capacity"].value
                + self._m["shed_late"].value)
        p99 = self.controller.last_p99_s or 0.0
        cur = self._current_lanes()
        hint = cur + 1 if (shed > 0 and p99 <= self.budget_s) else cur
        # the flight recorder's attribution engine is the second vote:
        # ingest/reorder-dominated e2e spread means the host side is the
        # variance source, and one more lane is the advisory fix even
        # without capacity sheds on record
        fr = getattr(pipe, "_flight", None)
        if fr is not None:
            hints = fr.attribution().get("hints", {})
            delta = int(hints.get("lanes_hint_delta", 0) or 0)
            if delta > 0:
                hint = max(hint, cur + delta)
        self._lanes_hint = hint

    # -- reporting ------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        c = self.controller
        return {
            "budget_ms": self.budget_ms,
            "admitted": int(self._m["admitted"].value),
            "rejected": int(self._m["rejected"].value),
            "shed_late": int(self._m["shed_late"].value),
            "shed_capacity": int(self._m["shed_capacity"].value),
            "service_time_ms": round(
                self.estimator.service_time_s() * 1e3, 3),
            "batch_cap": c.batch_cap,
            "mesh_quantum": self._mesh_quantum,
            "inflight_target": c.inflight,
            "controller_steps": c.steps,
            "p99_ms": round((c.last_p99_s or 0.0) * 1e3, 3),
            "lanes_hint": self._lanes_hint,
            "memory_hold": self._mem_hold,
        }

    def shed_total(self) -> int:
        return int(self._m["shed_late"].value
                   + self._m["shed_capacity"].value)

    # -- serving continuity ---------------------------------------------------
    # (checkpoint_state/restore_state, distinct from the reporting
    # snapshot() above — NNS115 checks the pair's key symmetry)
    def checkpoint_state(self) -> Dict[str, Any]:
        """The durable serving state a restarted process would otherwise
        re-learn from cold: the service-rate EWMAs and the controller's
        AIMD knobs/window, plus the advisory knob outputs. Counters stay
        in the metrics registry — they are observability, not state."""
        return {
            "estimator": self.estimator.snapshot(),
            "controller": self.controller.snapshot(),
            "lanes_hint": self._lanes_hint,
            "mem_hold": self._mem_hold,
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        est = state.get("estimator")
        if est:
            self.estimator.restore(est)
        ctl = state.get("controller")
        if ctl:
            self.controller.restore(ctl)
        self._mem_hold = int(state.get("mem_hold", 0))
        # push the restored inflight target onto the elements now —
        # otherwise the warm knobs only take effect after the first
        # post-restore controller step (this recomputes the lanes hint
        # from the fresh process's zeroed shed counters, so the saved
        # hint is applied after and the larger recommendation wins)
        self._apply_knobs()
        self._lanes_hint = max(self._lanes_hint,
                               int(state.get("lanes_hint", 0)))


def ensure_scheduler(pipeline) -> Optional[SloScheduler]:
    """Build (once) the pipeline's scheduler from its budget
    configuration: the pipeline-level ``slo_budget_ms`` wins, else the
    largest per-queue ``slo_budget_ms`` property. Returns None when no
    budget is configured — the kill switch: no scheduler object exists
    and every queue runs its exact pre-scheduler path."""
    existing = getattr(pipeline, "_slo_scheduler", None)
    if existing is not None:
        return existing
    budget = float(getattr(pipeline, "slo_budget_ms", 0.0) or 0.0)
    if budget <= 0:
        budget = max((float(el._props["slo_budget_ms"])
                      for el in pipeline.elements
                      if "slo_budget_ms" in el._props), default=0.0)
    if budget <= 0:
        return None
    sched = SloScheduler(budget_ms=budget, pipeline=pipeline)
    pipeline._slo_scheduler = sched
    log.info("%s: SLO scheduler attached (budget %.1f ms)",
             pipeline.name, budget)
    return sched
