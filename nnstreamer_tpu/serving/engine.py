"""Continuous-batching decode engine.

The TPU-first serving design, contrasted with the reference's query server
(one request = one pipeline invoke,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_server.c):

- **One static program.** ``max_streams`` batch slots share a single KV
  cache ``[L, 2, B, S, h, dh]`` in HBM. The hot loop is ONE jitted
  function whose shapes never change — no recompiles as streams come and
  go. Empty slots decode garbage that the host ignores; on a systolic
  array the wasted lanes cost nothing extra because the batched matmul
  runs anyway (utilization, not correctness, is what admission manages).
- **Multi-step dispatch.** Each dispatch runs ``steps_per_dispatch``
  decode steps under ``lax.scan`` and returns a ``[B, K]`` token block —
  per-call overhead (Python, transfer RPC on a tunneled chip) amortizes
  over K tokens. Streams hitting EOS mid-block waste at most K-1 slots of
  compute; the host truncates at the first EOS.
- **Bucketed prefill.** Prompts are right-padded to power-of-two buckets
  so prefill compiles once per bucket, not once per prompt length. Logits
  come from the true last position (``build_prefill`` lengths arg), and
  pad kv entries are provably unreachable (see models/transformer.py
  build_prefill docstring).
- **Slot-local determinism.** Each stream's PRNG key is derived from
  (engine seed, stream id), so sampled output is reproducible regardless
  of which other streams share the batch — per-stream results never
  depend on batch composition (the decode math is row-independent).

Host-side state (positions, last tokens, keys) is a handful of int32s
uploaded per dispatch; only the cache stays device-resident, donated into
every dispatch so XLA updates it in place.
"""

from __future__ import annotations

import collections
import itertools
import queue as _queue
import threading
import time as _time
import weakref
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from nnstreamer_tpu.log import get_logger

log = get_logger("serving")


class GenerationStream:
    """Handle for one submitted prompt: iterate to receive token ids as
    they are generated; ``None``-terminated internally."""

    _DONE = object()

    def __init__(self, stream_id: int, prompt_len: int):
        self.stream_id = stream_id
        self.prompt_len = prompt_len
        self.tokens: List[int] = []  # generated so far (post-prompt)
        #: chosen-token log-probabilities (model's own fp32 log_softmax,
        #: independent of temperature/top-k draw shaping), parallel to
        #: ``tokens``
        self.logprobs: List[float] = []
        self.finished = False
        self.finish_reason: Optional[str] = None  # "eos"|"length"|...
        self.cancelled = False
        self._q: _queue.Queue = _queue.Queue()

    def cancel(self) -> None:
        """Request cancellation (client gone, timeout, user abort): the
        engine frees this stream's batch slot at the next block boundary
        and finishes it with reason "cancelled". Pending (not yet
        admitted) streams are dropped without prefilling. Safe from any
        thread; idempotent; a no-op once finished."""
        self.cancelled = True

    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is self._DONE:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; returns all generated ids."""
        out = []
        deadline = None
        if timeout is not None:
            import time

            deadline = time.monotonic() + timeout
        while True:
            import time

            t = None if deadline is None else max(0.0,
                                                  deadline - time.monotonic())
            try:
                item = self._q.get(timeout=t)
            except _queue.Empty:
                raise TimeoutError(
                    f"stream {self.stream_id}: no token within {timeout}s")
            if item is self._DONE:
                return out
            out.append(item)

    # engine-side
    def _emit(self, tok: int, logprob: float = 0.0):
        self.tokens.append(tok)
        self.logprobs.append(logprob)
        self._q.put(tok)

    def _finish(self, reason: str):
        if self.finished:
            return  # idempotent: cancel/stop/EOS may race benignly
        self.finished = True
        self.finish_reason = reason
        self._q.put(self._DONE)


class _PrefixTrie:
    """Token trie over the prefix-cache keys: longest-common-prefix lookup
    in O(prompt_len), independent of entry count (the linear scan it
    replaces was O(entries × prompt_len) per admission).

    Each node counts the entries in its subtree and keeps a representative
    one (``rep``), so a lookup never descends below the walk: every entry
    in the deepest walkable node's subtree shares exactly the walked
    tokens with the prompt, i.e. all tie at the maximal LCP.
    """

    __slots__ = ("root",)

    @staticmethod
    def _node():
        return {"kids": {}, "entry": None, "count": 0, "rep": None}

    def __init__(self):
        self.root = self._node()

    def insert(self, key: tuple) -> None:
        node = self.root
        node["count"] += 1
        node["rep"] = key
        for tok in key:
            node = node["kids"].setdefault(tok, self._node())
            node["count"] += 1
            node["rep"] = key
        node["entry"] = key

    def remove(self, key: tuple) -> None:
        path = [self.root]
        node = self.root
        for tok in key:
            node = node["kids"][tok]
            path.append(node)
        node["entry"] = None
        for n in path:
            n["count"] -= 1
        # prune empty nodes; repair representatives that pointed at key
        for i in range(len(path) - 1, 0, -1):
            parent, child = path[i - 1], path[i]
            if child["count"] == 0:
                del parent["kids"][key[i - 1]]
        for n in path:
            if n["count"] > 0 and n["rep"] == key:
                n["rep"] = self._any_entry(n)

    @staticmethod
    def _any_entry(node):
        while node["entry"] is None:
            node = next(k for k in node["kids"].values() if k["count"] > 0)
        return node["entry"]

    def lookup(self, prompt) -> tuple:
        """→ (best_key, lcp): a cached key maximizing LCP with ``prompt``
        (an exact whole-prompt entry preferred), or (None, 0)."""
        node = self.root
        d = 0
        for tok in prompt:
            child = node["kids"].get(int(tok))
            if child is None:
                break
            node = child
            d += 1
        if d == 0 or node["count"] == 0:
            return None, 0
        if d == len(prompt) and node["entry"] is not None:
            return node["entry"], d  # exact match carries reusable logits
        return node["rep"], d


class _PendingRequest:
    def __init__(self, prompt: np.ndarray, max_new: int,
                 stream: GenerationStream):
        self.prompt = prompt
        self.max_new = max_new
        self.stream = stream
        self.submit_t = _time.monotonic()  # → queue-wait histogram


class ContinuousBatchingEngine:
    """Batched multi-stream generation over one transformer model.

    Parameters
    ----------
    cfg, params: a ``models.transformer`` config + param pytree.
    max_streams: batch slots (B). Static — sizes the cache and programs.
    max_seq: cache length S (defaults to ``cfg.max_seq``).
    steps_per_dispatch: decode steps fused into one device dispatch (K),
        or "auto" — start() measures the per-dispatch sync round trip
        and per-step decode time and picks K so the fixed dispatch cost
        amortizes to ≤~20% of a block (small on PCIe, large over a
        high-RTT link; see _calibrate_k).
    temperature / top_k / min_p: sampling config (``temperature<=0`` →
        greedy; see ``models.transformer.make_sampler``).
    eos_id: generation stops when the model emits this id (None → length
        -bounded only).
    seed: engine PRNG seed; per-stream keys fold in the stream id.
    min_bucket: smallest prefill padding bucket.
    mesh: optional ``jax.sharding.Mesh`` — multi-chip serving. Params
        shard per ``parallel.sharded.transformer_param_specs`` (heads/ffn
        over ``tp``), the KV cache shards batch slots over ``dp`` and
        heads over ``tp``, and GSPMD propagates through the unchanged
        decode/prefill programs ("computation follows data") — batched
        decode collectives ride ICI, never the host. Requires
        ``max_streams % dp == 0`` and ``n_heads % tp == 0``.
    prefill_chunk: when set, prompts ingest in fixed chunks of this many
        tokens, ONE chunk per engine-loop iteration, interleaved with
        decode dispatches — admitting a long prompt then adds at most
        one chunk's latency per block to running streams instead of a
        whole-prompt stall (and prefill compiles exactly once, at shape
        ``[1, chunk]``, instead of once per length bucket). Padded tail
        positions are unreachable-before-overwrite exactly like bucket
        padding. Requires ``prompt length <= max_seq - prefill_chunk``.
    kv_quant: ``"int8"`` stores the KV cache quantized (per-vector absmax
        scales) — ~2× batch slots or context per HBM byte, at a small,
        bounded numeric cost (models/transformer._Int8KVCodec).
    prefix_cache: keep the KV of the last N admitted prompts device-
        resident and, when a new prompt extends a cached one, prefill
        only the remainder — the multi-turn/system-prompt reuse pattern.
        Exact by construction: causal kv depends only on the prefix
        tokens, so reused entries are the same arrays a cold prefill
        would produce. HBM cost ≈ N × prompt_len × per-token kv bytes
        (LRU-evicted). 0 (default) disables.
    attention: prefill attention backend. "auto" (default) runs the
        Pallas flash kernel (ops/flash_attention.py) for the O(s²)
        prompt pass on TPU when the shapes tile (seq divisible by the
        block, head_dim ≤ 256), falling back to XLA attention
        elsewhere — long prompts stop materializing [s,s] score tiles
        in HBM. "reference" forces XLA attention everywhere. Decode and
        chunked ingestion keep the masked cache form (`_attend_cache`):
        their attention is over dynamically-positioned cache slots,
        which the causal-only kernel does not express.
    block_tokens: > 0 enables the PAGED KV cache (serving/kvpool.py):
        the cache becomes fixed-size blocks over one preallocated
        arena, per-stream block tables, admission bounded by FREE
        BLOCKS instead of batch slots — hundreds of streams time-share
        the B decode lanes under per-token EDF deadlines, and a shared
        prompt prefix costs its blocks once (copy-on-write block
        tables). 0 (default) or ``NNSTPU_PAGED_KV=0`` keeps the
        monolithic cache byte-identical to the unpaged engine.
    kv_blocks: arena size in blocks (paged mode). Defaults to
        ``max_streams * max_seq / block_tokens`` — the same HBM bytes
        the monolithic cache would take.
    speculate: > 0 enables speculative decoding — a ``speculate_layers``
        -layer draft sliced from the target params
        (models/speculative.py) proposes K tokens per round inside the
        batched decode; the target verifies them in ONE chunk pass.
        Greedy only (temperature must be 0), single-chip only, and
        concurrency is capped at ``max_streams`` (the draft cache is
        slot-structured). Output is byte-identical to non-speculative
        greedy decoding by construction.
    """

    def __init__(self, cfg, params, max_streams: int = 4,
                 max_seq: Optional[int] = None,
                 steps_per_dispatch: int = 8,
                 temperature: float = 0.0, top_k: int = 0,
                 min_p: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 min_bucket: int = 16, mesh=None,
                 prefill_chunk: Optional[int] = None,
                 kv_quant: Optional[str] = None,
                 prefix_cache: int = 0,
                 attention: str = "auto",
                 slo_budget_ms: float = 0.0,
                 block_tokens: int = 0,
                 kv_blocks: Optional[int] = None,
                 speculate: int = 0,
                 speculate_layers: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_chunk_decode,
            build_decode_step,
            build_prefill,
            init_cache,
        )

        self.cfg = cfg
        self.params = params
        self.B = int(max_streams)
        self.S = int(max_seq or cfg.max_seq)
        #: steps_per_dispatch="auto": start() measures the per-dispatch
        #: sync round trip and the per-step decode time, then picks K so
        #: the fixed dispatch cost amortizes (see _calibrate_k) — on a
        #: PCIe-attached chip that lands small, on a high-RTT link large
        self._auto_k = steps_per_dispatch == "auto"
        self.K = 8 if self._auto_k else int(steps_per_dispatch)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.min_p = float(min_p)
        self.eos_id = eos_id
        self.seed = int(seed)
        self.min_bucket = int(min_bucket)

        self.prefill_chunk = None if prefill_chunk is None \
            else int(prefill_chunk)
        if self.prefill_chunk is not None and not (
                0 < self.prefill_chunk < self.S):
            raise ValueError(
                f"serving: prefill_chunk must be in (0, {self.S}), got "
                f"{prefill_chunk}")
        self.kv_quant = kv_quant
        if attention not in ("auto", "reference"):
            raise ValueError(
                f"serving: attention must be 'auto' or 'reference', got "
                f"{attention!r}")
        attention_fn = None
        if attention == "auto" and mesh is None:
            # single-chip only: pallas_call does not carry GSPMD
            # partitioning rules, so the meshed engine keeps XLA
            # attention (which GSPMD shards like the rest of prefill)
            from nnstreamer_tpu.ops import flash_attention

            attention_fn = flash_attention  # causal=True is its default
        self._decode = build_decode_step(cfg, self.S, kv_codec=kv_quant)
        self._prefill_fn = build_prefill(cfg, self.S,
                                         attention_fn=attention_fn,
                                         kv_codec=kv_quant)
        self._chunk_fn = build_chunk_decode(cfg, self.S, kv_codec=kv_quant)
        #: in-progress chunked admission: (request, slot, cache1, k) with
        #: k = next chunk index; one at a time, advanced between dispatches
        self._partial = None

        from nnstreamer_tpu.serving import kvpool as _kvpool

        self.block_tokens = int(block_tokens or 0)
        #: paged KV cache on: block_tokens > 0 AND the env kill switch
        #: (NNSTPU_PAGED_KV) allows it. Off → every code path below is
        #: the unchanged monolithic engine.
        self.paged = self.block_tokens > 0 and _kvpool.paged_enabled()
        self._pool = None
        if self.paged:
            if self.S % self.block_tokens:
                raise ValueError(
                    f"serving: block_tokens ({self.block_tokens}) must "
                    f"divide max_seq ({self.S})")
            from nnstreamer_tpu.models.transformer import (
                build_paged_chunk,
                build_paged_decode_step,
            )

            #: block-table width: blocks per stream at full context
            self.MB = self.S // self.block_tokens
            self._paged_decode = build_paged_decode_step(
                cfg, self.block_tokens, self.S, kv_codec=kv_quant)
            self._paged_chunk_fn = build_paged_chunk(
                cfg, self.block_tokens, self.S, kv_codec=kv_quant)
            nb = int(kv_blocks) if kv_blocks else self.B * self.MB
            if mesh is not None and "dp" in mesh.axis_names:
                # arena block axis shards over dp: pad so NTOT divides
                nb += (-(nb + 1)) % mesh.shape["dp"]
            self._num_blocks = nb

        # host-side per-slot state
        self._pos = np.zeros(self.B, np.int32)
        self._last = np.zeros(self.B, np.int32)
        #: device-resident decode feedback (last, pos, keys) chaining
        #: dispatch N+1 off dispatch N without a host sync; None = host
        #: mirrors are authoritative (after admissions/recovery)
        self._dev_state = None
        #: issued-but-unprocessed dispatch blocks:
        #: (t0, toks, lps, [(slot, stream), ...]) — host processing runs
        #: one block behind so the fetch RTT overlaps the next compute
        self._inflight: "collections.deque" = collections.deque()
        self._keys = np.zeros((self.B, 2), np.uint32)
        self._slots: List[Optional[GenerationStream]] = [None] * self.B
        self._budget = np.zeros(self.B, np.int64)  # tokens still allowed

        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            from nnstreamer_tpu.parallel import serve as _serve
            from nnstreamer_tpu.parallel.sharded import (
                transformer_param_specs,
            )

            def axis(name, dim, total):
                if name not in mesh.axis_names or mesh.shape[name] <= 1:
                    return None
                if total % mesh.shape[name]:
                    raise ValueError(
                        f"serving: {dim} ({total}) must divide by mesh "
                        f"axis {name!r} ({mesh.shape[name]})")
                return name

            dp = axis("dp", "max_streams", self.B)
            tp = axis("tp", "n_heads", cfg.n_heads)

            def prune(spec):
                # drop axis names the mesh doesn't have (e.g. a dp-only
                # serving mesh has no "tp"; a dense model's mesh no "ep")
                # — absent axis = replicated on that dimension
                return P(*(a if (a is not None and a in mesh.axis_names)
                           else None for a in spec))

            specs = {k: prune(s)
                     for k, s in transformer_param_specs(cfg).items()}
            # serving-plane placement (parallel/serve.py): per-shard HBM
            # registers with the budget accountant when one is active
            self.params = _serve.place_params(params, mesh, specs,
                                              label="engine:lm")

            def shard_cache(cache):
                # cache leaves: values [L,2,B,S,h,dh] and (int8 codec)
                # scales [L,2,B,S,h] — same prefix, so slice the spec to
                # each leaf's rank. Working state the engine resizes on
                # its own schedule — placed, not budget-registered.
                full = (None, None, dp, None, tp, None)
                return _serve.place_tree(
                    cache, mesh, lambda a: P(*full[:a.ndim]),
                    label="engine:kv-cache")

            self._init_cache = lambda: shard_cache(
                init_cache(cfg, self.B, self.S, kv_codec=kv_quant))
        else:
            self._init_cache = lambda: init_cache(cfg, self.B, self.S,
                                                  kv_codec=kv_quant)
        # paged mode never materializes the monolithic [L,2,B,S,...]
        # cache — the arena (created below, after obs_name) is the only
        # KV storage
        self._cache = None if self.paged else self._init_cache()
        self._pending: "_queue.Queue[_PendingRequest]" = _queue.Queue()
        self._next_id = 0
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, Any] = {
            "tokens_generated": 0, "dispatches": 0, "prefills": 0,
            "prefill_chunks": 0, "slot_steps": 0, "active_slot_steps": 0,
            "prefix_hits": 0, "prefix_tokens_reused": 0,
            "concurrent_streams_max": 0, "kv_sheds": 0, "kv_defers": 0,
            "spec_drafted": 0, "spec_accepted": 0,
        }
        from nnstreamer_tpu.obs import (
            get_registry,
            register_engine_collector,
        )

        #: registry label distinguishing concurrent engines in one process
        self.obs_name = f"engine{next(self._OBS_SEQ)}"
        self._m_queue_wait = get_registry().histogram(
            "nns_serving_queue_wait_seconds",
            "submit() to batch-slot admission wait",
            engine=self.obs_name)
        register_engine_collector(self)
        #: request-path SLO admission (serving/scheduler.py): submit()
        #: rejects prompts whose deadline is unmeetable under the EWMA
        #: per-request service estimate; 0 = admit everything (default)
        self._slo = None
        if float(slo_budget_ms or 0.0) > 0:
            from nnstreamer_tpu.serving.scheduler import SloScheduler

            self._slo = SloScheduler(budget_ms=float(slo_budget_ms),
                                     name=self.obs_name)
        from nnstreamer_tpu.obs.flight import LMTokenStats

        #: per-token latency quantiles (TTFT vs inter-token split) —
        #: nns_lm_ttft_p50/p99_ms, nns_lm_token_p50/p99_ms
        self._lm_stats = LMTokenStats(self.obs_name)
        self._mesh = mesh
        if self.paged:
            self._pool = _kvpool.BlockPool(
                cfg, self._num_blocks, self.block_tokens,
                kv_codec=kv_quant, mesh=mesh, owner=self.obs_name)
            #: sid → per-stream decode state (stream, blocks, pos, last,
            #: key, budget, deadline_t, slot); engine thread only. Every
            #: ADMITTED stream lives here whether or not it currently
            #: holds one of the B decode lanes.
            self._sstate: Dict[int, dict] = {}
            #: admission head deferred on block exhaustion (FIFO order
            #: is preserved: nothing behind it admits until it fits)
            self._held: Optional[_PendingRequest] = None
            #: decode lane → sid occupying it (None = free lane)
            self._lane: List[Optional[int]] = [None] * self.B
            #: host mirror of the device block tables, one row per lane
            self._bt = np.full((self.B, self.MB), self._pool.SENTINEL,
                               np.int32)
        self.prefix_cache = int(prefix_cache)
        if self.prefix_cache < 0:
            raise ValueError(
                f"serving: prefix_cache must be >= 0, got {prefix_cache}")
        #: tuple(prompt ids) → (kv pytree [L,2,1,n,...], logits[1,V]) —
        #: LRU, engine-thread only; the trie mirrors the key set for
        #: O(prompt_len) longest-common-prefix admission lookups
        self._prefix: "collections.OrderedDict" = collections.OrderedDict()
        self._prefix_trie = _PrefixTrie()
        from nnstreamer_tpu.utils.stats import InvokeStats

        #: reference-style windowed read-outs (latency_us = one [B,K]
        #: dispatch wall time incl. the token fetch; throughput_milli =
        #: dispatches/s ×1000) — the SAME instrument every pipeline
        #: element exposes (utils/stats.py), so engine and element
        #: metrics read uniformly
        self.invoke_stats = InvokeStats()

        from nnstreamer_tpu.models.transformer import make_sampler

        decode = self._decode
        # the ONE sampling function (shared with the repo-loop sampled
        # step) — seeds the first token and every dispatch-loop draw with
        # identical math, per-row keys keeping streams batch-independent
        sample = make_sampler(cfg.vocab, self.temperature, self.top_k,
                              self.min_p, with_logprobs=True)

        def build_dispatch(K):
            def dispatch(params, token, cache, pos, keys):
                """K decode steps in one program: ([B],cache,[B],[B,2]) →
                ([B,K] tokens, [B,K] logprobs, cache, keys, last, pos').

                The final carry (last token, advanced pos) comes back as
                DEVICE arrays so the next dispatch can chain off them
                without waiting for the token fetch — the loop pipelines
                the host materialization one block behind the device
                (engine _loop)."""

                def body(carry, _):
                    token, cache, pos, keys = carry
                    logits, cache = decode(params, token, cache, pos)
                    nxt, keys, lp = sample(logits, keys)
                    return (nxt, cache, pos + 1, keys), (nxt, lp)

                (token, cache, pos, keys), (toks, lps) = jax.lax.scan(
                    body, (token, cache, pos, keys), None, length=K)
                return (jnp.transpose(toks), jnp.transpose(lps), cache,
                        keys, token, pos)

            return jax.jit(dispatch, donate_argnums=(2,))

        self._build_dispatch = build_dispatch
        if self.paged:
            paged_decode = self._paged_decode

            def build_paged_dispatch(K):
                def dispatch(params, token, arena, bt, pos, keys):
                    """Paged twin of the mono dispatch: same K-step scan,
                    cache replaced by (arena, block tables). bt is LOOP-
                    INVARIANT across the K steps — the loop tops up every
                    bound stream's blocks through pos+K-1 first."""

                    def body(carry, _):
                        token, arena, pos, keys = carry
                        logits, arena = paged_decode(params, token, arena,
                                                     bt, pos)
                        nxt, keys, lp = sample(logits, keys)
                        return (nxt, arena, pos + 1, keys), (nxt, lp)

                    (token, arena, pos, keys), (toks, lps) = jax.lax.scan(
                        body, (token, arena, pos, keys), None, length=K)
                    return (jnp.transpose(toks), jnp.transpose(lps),
                            arena, keys, token, pos)

                return jax.jit(dispatch, donate_argnums=(2,))

            self._build_dispatch = build_paged_dispatch
            self._dispatch = build_paged_dispatch(self.K)
            self._paged_chunk_jitted = jax.jit(self._paged_chunk_fn,
                                               donate_argnums=(2,))
        else:
            self._dispatch = build_dispatch(self.K)
        self._sample_first = jax.jit(sample)

        def insert(cache, cache1, slot):
            # tree-aware: raw caches are one [L,2,B,S,h,dh] array; the
            # int8 codec adds a rank-5 scales leaf — slot is batch axis 2
            # in every leaf
            return jax.tree.map(
                lambda c, u: jax.lax.dynamic_update_slice(
                    c, u.astype(c.dtype),
                    (0, 0, slot) + (0,) * (c.ndim - 3)), cache, cache1)

        self._insert = jax.jit(insert, donate_argnums=(0,))

        # one jitted prefill; XLA caches one executable per bucket shape
        self._prefill_jitted = jax.jit(self._prefill_fn)
        # chunked-prefill program: ONE executable at shape [1, chunk]
        self._chunk_jitted = jax.jit(self._chunk_fn, donate_argnums=(2,))
        self._jnp = jnp
        self._jax = jax

        #: monolithic prefix-cache HBM accounting (tensors/memory.py
        #: "kvcache" category): tuple key → (acct_key, nbytes). Paged
        #: entries skip this — their blocks are arena bytes the pool
        #: already registered.
        self._prefix_acct: Dict[tuple, tuple] = {}
        self._prefix_seq = itertools.count()
        #: prefix keys the accountant dropped under pressure (on_drop
        #: fires on an arbitrary thread; the engine thread reaps)
        self._condemned: set = set()
        self._condemned_lock = threading.Lock()

        self.speculate = 0
        self._speculate_layers: Optional[int] = None
        self._spec: Optional[dict] = None
        if int(speculate or 0) > 0:
            self.set_speculate(int(speculate), speculate_layers)

    def _calibrate_k(self) -> None:
        """steps_per_dispatch="auto": pick K from MEASURED costs.

        A decode block costs ``rtt + K·s`` wall time for ``rtt`` = the
        fixed dispatch+sync overhead (dominated by the host↔device link;
        ~0.1 ms on PCIe, tens of ms through a tunnel) and ``s`` = one
        batched decode step. ``rtt`` is timed with a trivial synced
        device program; ``s`` falls out of one timed block at the
        initial K. K is then chosen so the fixed cost is ≤ ~20% of the
        block (K ≥ 4·rtt/s), clamped to [8, 128] and rounded down to a
        power of two (bucketed executables). Runs once, before the
        engine loop starts, on the LIVE cache (safe because _insert
        fully overwrites a slot's KV at admission — see below)."""
        import numpy as _np
        import time as _time

        jax, jnp = self._jax, self._jnp
        tiny = jax.jit(lambda x: x + 1)
        x = jnp.zeros((8,), jnp.int32)
        _np.asarray(tiny(x))  # compile off the clock
        rtt = min(
            (lambda t0: (_np.asarray(tiny(x)), _time.monotonic() - t0)[1])(
                _time.monotonic()) for _ in range(3))
        # calibrate on the LIVE cache (no streams are active before
        # start(), and every slot is fully overwritten at admission by
        # _insert) — a throwaway cache would transiently double KV HBM
        # and OOM exactly the memory-tight configs auto-K serves
        token = jnp.zeros((self.B,), jnp.int32)
        pos = jnp.zeros((self.B,), jnp.int32)
        keys = jnp.zeros((self.B, 2), jnp.uint32)
        # dispatch DONATES the cache/arena: reassign immediately after
        # each call so a failure mid-calibration never leaves it
        # pointing at deleted buffers (start() also reinits on error)
        if self.paged:
            # all-sentinel block tables: writes drop, reads hit the zero
            # block — a pure timing run that cannot corrupt the arena
            bt = jnp.full((self.B, self.MB), self._pool.SENTINEL,
                          jnp.int32)

            def run():
                out = self._dispatch(self.params, token,
                                     self._pool.arena, bt, pos, keys)
                self._pool.arena = out[2]
                return out
        else:
            def run():
                out = self._dispatch(self.params, token, self._cache,
                                     pos, keys)
                self._cache = out[2]
                return out
        out = run()
        _np.asarray(out[0])  # compile + warm
        t0 = _time.monotonic()
        out = run()
        _np.asarray(out[0])
        block = _time.monotonic() - t0
        step = max((block - rtt) / self.K, 1e-5)
        k = max(8, min(128, int(4 * rtt / step)))
        k = 1 << (k.bit_length() - 1)  # round down to a power of two
        log.info("serving: auto K — rtt %.2f ms, step %.3f ms → K=%d",
                 rtt * 1e3, step * 1e3, k)
        if k != self.K:
            self.K = k
            self._dispatch = self._build_dispatch(k)

    # -- public API -----------------------------------------------------------
    def start(self) -> "ContinuousBatchingEngine":
        if self._thread is not None and not self._thread.is_alive():
            # leftover from a timed-out stop() whose loop has since
            # exited: reap it so restart works instead of silently no-op
            self._thread.join(timeout=0)
            self._thread = None
        if self._thread is not None:
            if self._stop_evt.is_set():
                raise RuntimeError(
                    "serving: previous engine loop is still shutting "
                    "down; retry start() after it exits")
            return self  # already running
        if self._auto_k:
            self._auto_k = False  # calibrate once, not per restart
            try:
                self._calibrate_k()
            except Exception as e:  # noqa: BLE001 — auto-tune is an
                # optimization; the initial K always works
                log.warning("serving: K auto-calibration failed (%s); "
                            "keeping K=%d", e, self.K)
                # the failed dispatch may have donated (deleted) the
                # live cache's buffers or left error arrays in it;
                # release the old reference BEFORE reallocating so the
                # two caches never coexist (HBM headroom)
                if self.paged:
                    self._pool.reset()
                else:
                    self._cache = None
                    self._cache = self._init_cache()
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="cb-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # stuck in a long compile/dispatch: keep the thread ref so
                # a later start() can't spawn a concurrent second loop,
                # and leave stream state to the still-running loop
                log.warning("serving: engine loop still busy at stop(); "
                            "call stop() again after it settles")
                return
            self._thread = None
        # fail any stream still in flight so iterators don't hang; the
        # lock serializes with submit()'s running-check + enqueue, so a
        # request can't slip into _pending after this drain
        with self._lock:
            if self._partial is not None:
                self._partial[0].stream._finish("engine-stopped")
                self._partial = None
            for i, st in enumerate(self._slots):
                if st is self._RESERVED:
                    self._slots[i] = None
                elif st is not None and not st.finished:
                    st._finish("engine-stopped")
                    self._slots[i] = None
            if self.paged:
                for state in list(self._sstate.values()):
                    self._finish_paged(state, "engine-stopped")
                if self._held is not None:
                    self._held.stream._finish("engine-stopped")
                    self._held = None
            while True:
                try:
                    req = self._pending.get_nowait()
                except _queue.Empty:
                    break
                req.stream._finish("engine-stopped")

    def submit(self, prompt, max_new_tokens: int = 64) -> GenerationStream:
        """Queue a prompt (sequence of int token ids); returns a
        :class:`GenerationStream` yielding generated ids."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("serving: empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"serving: max_new_tokens must be >= 1, got {max_new_tokens}"
                " (the prefill always yields the first token)")
        # chunked mode: the last chunk's writes (ceil(n/C)*C slots) must
        # fit the cache — equal to the plain n < S bound when C divides S
        limit = self.S - 1 if self.prefill_chunk is None else min(
            self.S - 1, (self.S // self.prefill_chunk) * self.prefill_chunk)
        if self.speculate:
            # a verify chunk writes kv at positions [pos, pos+K]; the
            # per-stream budget keeps pos <= S-1-K only if admission does
            limit = min(limit, self.S - 1 - self.speculate)
        if prompt.size > limit:
            raise ValueError(
                f"serving: prompt length {prompt.size} must be <= {limit} "
                f"(cache length {self.S}"
                + (f", prefill chunk {self.prefill_chunk})"
                   if self.prefill_chunk is not None else ")"))
        with self._lock:
            # running-check + enqueue under the same lock stop() drains
            # under, so a request can't land after the drain (it would
            # never be admitted or finished)
            if self._thread is None or self._stop_evt.is_set():
                raise RuntimeError(
                    "serving: engine is not running — call start() first "
                    "(a submit with no loop thread would never complete)")
            if self._slo is not None:
                # backlog ahead of this request: queued + active streams
                # (raises SloRejected before any slot/queue capacity is
                # consumed — overload is turned away at the door, not
                # discovered as a latency outlier)
                backlog = self._pending.qsize() + (
                    len(self._sstate) + (1 if self._held is not None
                                         else 0)
                    if self.paged else
                    sum(1 for s in self._slots if s is not None))
                self._slo.admit_request(_time.monotonic(), backlog)
            sid = self._next_id
            self._next_id += 1
            stream = GenerationStream(sid, prompt.size)
            stream.submit_t = _time.monotonic()  # → SLO service estimate
            self._pending.put(_PendingRequest(prompt, int(max_new_tokens),
                                              stream))
        self._wake.set()
        return stream

    def generate(self, prompt, max_new_tokens: int = 64,
                 timeout: Optional[float] = None) -> List[int]:
        """Synchronous helper: submit + wait (engine must be started)."""
        return self.submit(prompt, max_new_tokens).result(timeout=timeout)

    @property
    def active_streams(self) -> int:
        if self.paged:
            return len(self._sstate)
        return sum(1 for s in self._slots
                   if s is not None and s is not self._RESERVED)

    # -- engine internals ------------------------------------------------------
    def _bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return min(b, self.S)

    # -- prefix cache (engine thread only) ------------------------------------
    def _prefix_lookup(self, prompt: np.ndarray):
        """Longest COMMON prefix between ``prompt`` and any cached entry
        (two different user prompts sharing a system preamble still
        reuse the shared part); returns (p, kv sliced to p, logits) —
        logits only when the whole prompt equals a whole stored key."""
        best_key, best_lcp = self._prefix_trie.lookup(prompt)
        if best_key is None or best_lcp <= 0:
            return 0, None, None
        self._prefix.move_to_end(best_key)
        kv, logits = self._prefix[best_key]
        if not (best_lcp == prompt.size == len(best_key)):
            logits = None
        if logits is None and best_lcp == prompt.size:
            # whole prompt covered by a LONGER stored key: we have its kv
            # but not its last-position logits — recompute one position
            best_lcp -= 1
        if best_lcp < len(best_key):
            kv = self._jax.tree.map(lambda a: a[:, :, :, :best_lcp], kv)
        if best_lcp <= 0:
            return 0, None, None
        return best_lcp, kv, logits

    def _prefix_store(self, prompt: np.ndarray, cache1, logits):
        if not self.prefix_cache:
            return
        key = tuple(int(t) for t in prompt)
        n = prompt.size
        # slice slot-S down to the prompt's n positions (axis 3 = S in
        # every cache leaf, values and int8 scales alike)
        kv = self._jax.tree.map(lambda a: a[:, :, :, :n], cache1)
        if key not in self._prefix:
            self._prefix_trie.insert(key)
        else:
            self._prefix_unaccount(key)  # re-stored: bytes change
        self._prefix[key] = (kv, logits)
        self._prefix.move_to_end(key)
        self._prefix_account(key, kv)
        while len(self._prefix) > self.prefix_cache:
            evicted, _ = self._prefix.popitem(last=False)
            self._prefix_trie.remove(evicted)
            self._prefix_unaccount(evicted)

    # -- prefix-cache HBM accounting (tensors/memory.py, "kvcache") ----------
    def _prefix_account(self, key: tuple, kv) -> None:
        """Register one monolithic prefix entry's device bytes with the
        HBM accountant as a DROPPABLE unit: under pressure the
        accountant revokes it (on_drop condemns the key; the engine
        thread reaps), so cached prefixes ride the evict rung of the
        pressure ladder instead of being invisible HBM."""
        from nnstreamer_tpu.tensors import memory as _memory

        acct = _memory.ACTIVE
        if acct is None:
            return
        nbytes = _memory.pytree_nbytes(kv)
        acct_key = f"{self.obs_name}:prefix:{next(self._prefix_seq)}"
        ref = weakref.ref(self)

        def on_drop(_k, key=key):
            eng = ref()
            if eng is not None:
                with eng._condemned_lock:
                    eng._condemned.add(key)

        acct.residency.register_droppable(
            acct_key, nbytes, on_drop, label=f"{self.obs_name}:prefix")
        self._prefix_acct[key] = (acct_key, nbytes)

    def _prefix_unaccount(self, key: tuple) -> None:
        rec = self._prefix_acct.pop(key, None)
        if rec is None:
            return
        from nnstreamer_tpu.tensors import memory as _memory

        acct = _memory.ACTIVE
        if acct is not None:
            acct.residency.unregister(rec[0])

    def _reap_condemned(self) -> None:
        """Engine-thread half of droppable prefix eviction: drop the
        entries whose accounting units the pressure ladder revoked.
        (Their bytes are already un-registered — only the engine's
        references remain to release.)"""
        if not self._condemned:
            return
        with self._condemned_lock:
            keys = list(self._condemned)
            self._condemned.clear()
        for key in keys:
            self._prefix_acct.pop(key, None)
            if key in self._prefix:
                del self._prefix[key]
                self._prefix_trie.remove(key)

    def _place_prefix_kv(self, cache1, kv):
        """Write a cached kv slice into slots [0, n) of a fresh cache."""
        jax = self._jax
        return jax.tree.map(
            lambda c, u: jax.lax.dynamic_update_slice(
                c, u.astype(c.dtype), (0,) * c.ndim), cache1, kv)

    def _admit(self, req: _PendingRequest, slot: int):
        """Device phase of one admission: prefill (or prefix reuse) and
        first-token sampling DISPATCH. Returns the activation record for
        :meth:`_activate_commit` — the loop commits a whole admission
        wave with one host sync instead of one round trip per prompt."""
        self._m_queue_wait.observe(_time.monotonic() - req.submit_t)
        jnp = self._jnp
        prompt = req.prompt
        n = prompt.size
        p, kv, cached_logits = (self._prefix_lookup(prompt)
                                if self.prefix_cache else (0, None, None))
        if p == n:  # whole prompt cached: zero prefill compute
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_reused"] += p
            cache1 = self._place_prefix_kv(self._init_cache1(), kv)
            return self._activate_begin(req, slot, cached_logits, cache1)
        if (p >= self.PREFIX_MIN_REUSE
                and p + self._bucket(n - p) <= self.S):
            # prefill only the remainder through the chunk program. The
            # first bound skips near-useless hits (a 1-token overlap
            # costs a cache copy to save one token of an already-compiled
            # prefill — the chunked path's sub-chunk-is-a-miss rule,
            # bucketed flavor); the second keeps the padded chunk's
            # writes inside the cache (a near-capacity prompt just takes
            # the cold path)
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_reused"] += p
            cache1 = self._place_prefix_kv(self._init_cache1(), kv)
            rem = n - p
            bucket = self._bucket(rem)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :rem] = prompt[p:]
            logits, cache1 = self._chunk_jitted(
                self.params, jnp.asarray(padded), cache1,
                jnp.asarray(p, jnp.int32))
            logits = logits[:, rem - 1]
            self._prefix_store(prompt, cache1, logits)
            return self._activate_begin(req, slot, logits, cache1)
        bucket = self._bucket(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = prompt
        logits, cache1 = self._prefill_jitted(
            self.params, jnp.asarray(padded),
            lengths=jnp.asarray([n], jnp.int32))
        self._prefix_store(prompt, cache1, logits)
        return self._activate_begin(req, slot, logits, cache1)

    def _init_cache1(self):
        from nnstreamer_tpu.models.transformer import init_cache

        return init_cache(self.cfg, 1, self.S, kv_codec=self.kv_quant)

    #: reserves a batch slot while its chunked prefill is in flight
    _RESERVED = object()

    #: process-wide sequence behind ``obs_name`` (engine0, engine1, ...)
    _OBS_SEQ = itertools.count()

    #: minimum common-prefix length worth a warm (remainder-only)
    #: admission; exact whole-prompt hits are never thresholded
    PREFIX_MIN_REUSE = 4

    def _begin_partial(self, req: _PendingRequest, slot: int):
        self._m_queue_wait.observe(_time.monotonic() - req.submit_t)
        base = 0
        cache1 = self._init_cache1()
        if self.prefix_cache:
            p, kv, cached_logits = self._prefix_lookup(req.prompt)
            if p == req.prompt.size:  # whole prompt cached: no chunks
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_reused"] += p
                cache1 = self._place_prefix_kv(cache1, kv)
                self._activate(req, slot, cached_logits, cache1)
                return
            elif (p // self.prefill_chunk) > 0:
                # resume at the last chunk boundary <= p: chunk starts
                # stay multiples of C (the submit-time bound assumes it),
                # recomputing at most C-1 cached positions. A hit below
                # one chunk (base would be 0) is a miss — nothing reusable
                self.stats["prefix_hits"] += 1
                base = (p // self.prefill_chunk) * self.prefill_chunk
                self.stats["prefix_tokens_reused"] += base
                cache1 = self._place_prefix_kv(cache1, kv)
        self._slots[slot] = self._RESERVED
        self._partial = (req, slot, cache1, 0, base)

    def _advance_partial(self):
        """Run ONE prefill chunk; on the last chunk, activate the slot."""
        jnp = self._jnp
        req, slot, cache1, k, base = self._partial
        C = self.prefill_chunk
        prompt, n = req.prompt, req.prompt.size
        start = base + k * C
        end = min(start + C, n)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :end - start] = prompt[start:end]
        try:
            logits, cache1 = self._chunk_jitted(
                self.params, jnp.asarray(chunk), cache1,
                jnp.asarray(start, jnp.int32))
            self.stats["prefill_chunks"] += 1
            if end < n:
                self._partial = (req, slot, cache1, k + 1, base)
                return
            # final chunk: logits at the prompt's true last position
            self._partial = None
            logits_last = logits[:, (n - 1) - start]
            if self.paged:
                rec = self._activate_paged_from_cache1(req, logits_last,
                                                       cache1)
                if rec is None:  # pool exhausted: re-ingest when it isn't
                    self.stats["kv_defers"] += 1
                    self._held = req
                else:
                    self._activate_commit_paged(rec)
                return
            self._prefix_store(prompt, cache1, logits_last)
            self._activate(req, slot, logits_last, cache1)
        except Exception as e:  # noqa: BLE001 — a failed chunk must free
            # the reserved slot and fail only this request
            log.warning("serving: chunked prefill failed: %s", e)
            self._partial = None
            if slot is not None:
                self._slots[slot] = None
            req.stream._finish(f"error: {e}")

    def _activate_begin(self, req: _PendingRequest, slot: int, logits,
                        cache1):
        """Device half of an activation: dispatch the first-token sample
        and the cache insert, CLAIM the slot, and return the record
        ``(req, slot, first_d, key_d, lp_d)`` whose device handles
        :meth:`_activate_commit` materializes. Splitting lets an
        admission wave share one host sync (grouped fetch) instead of
        paying a full link round trip per prompt."""
        jnp = self._jnp
        key = np.asarray(
            [self.seed & 0xFFFFFFFF, req.stream.stream_id & 0xFFFFFFFF],
            np.uint32)[None]
        first_d, key_d, lp_d = self._sample_first(logits,
                                                  jnp.asarray(key))
        # dtype alignment happens inside the tree-aware _insert
        self._cache = self._insert(self._cache, cache1, slot)
        if self._spec is not None:
            # the shallow draft re-reads the whole prompt (cheap: half
            # the layers, one bucketed prefill) so its cache is
            # canonical from position 0
            self._draft_prefill(req, slot)
        self._slots[slot] = req.stream  # claimed; mirrors land at commit
        return (req, slot, first_d, key_d, lp_d)

    def _activate_commit(self, rec) -> None:
        """Host half: materialize the sampled first token and install
        the per-slot host mirrors. Callers must run
        :meth:`_sync_host_state` after the begins and before the first
        commit — this is the one place per-slot host state is written,
        and syncing at commit time (not at a check-then-act distance
        from the pending queue) closes the race where a submit() lands
        after the loop's emptiness check; the dispatch that follows any
        activation always rebuilds its device state from the mirrors."""
        req, slot, first_d, key_d, lp_d = rec
        n = req.prompt.size
        self.stats["prefills"] += 1
        first = int(np.asarray(first_d)[0])
        first_lp = float(np.asarray(lp_d)[0])
        self._pos[slot] = n
        self._last[slot] = first
        self._keys[slot] = np.asarray(key_d)[0]
        # cap generation so cache writes stay inside the slot's S window
        # (a speculative verify chunk writes through pos+K, hence the
        # extra margin; zero when speculation is off)
        self._budget[slot] = min(req.max_new, self.S - n - self.speculate)
        t0 = getattr(req.stream, "submit_t", None)
        if t0 is not None:
            self._lm_stats.observe_ttft(_time.monotonic() - t0)
        req.stream._emit(first, first_lp)
        self.stats["tokens_generated"] += 1
        self._post_emit(slot, first)

    def _activate(self, req: _PendingRequest, slot: int, logits, cache1):
        """Single-admission tail (chunked-prefill path): begin + one
        host sync + commit."""
        rec = self._activate_begin(req, slot, logits, cache1)
        self._sync_host_state()
        self._activate_commit(rec)

    def _post_emit(self, slot: int, tok: int):
        """Budget/EOS bookkeeping after a token reaches its stream. The
        slot is freed BEFORE _finish wakes the client, so a caller that
        observes its stream done also observes the slot released."""
        st = self._slots[slot]
        self._budget[slot] -= 1
        done = (self.eos_id is not None and tok == self.eos_id) or \
            self._budget[slot] <= 0
        if done and self._slo is not None:
            t0 = getattr(st, "submit_t", None)
            if t0 is not None:
                # whole-request service time feeds the admission EWMA
                # (and the controller's p99 window) — per-REQUEST, since
                # the engine's admission unit is a request, not a frame
                now = _time.monotonic()
                self._slo.observe_completion(now - t0, now, frames=1)
                self._slo.observe_service(now - t0, frames=1)
        if self.eos_id is not None and tok == self.eos_id:
            self._slots[slot] = None
            st._finish("eos")
        elif self._budget[slot] <= 0:
            self._slots[slot] = None
            st._finish("length")

    # -- pipelined block processing -------------------------------------------
    def _process_block(self, t0, toks_dev, lps_dev, snapshot):
        """Materialize one dispatched block and emit its tokens to the
        streams that were active when it was ISSUED (a slot freed or
        re-admitted since then skips emission — its tokens were garbage
        or belong to a stream that already finished)."""
        toks = np.asarray(toks_dev)  # the D2H sync; timed below
        lps = np.asarray(lps_dev)
        dt = _time.monotonic() - t0
        self.invoke_stats.record(dt)
        self.stats["dispatches"] += 1
        self.stats["slot_steps"] += self.B * self.K
        per_tok = dt / self.K
        for slot, st in snapshot:
            if self._slots[slot] is not st:
                continue  # freed/replaced while the block was in flight
            self._lm_stats.observe_token(per_tok)
            self._pos[slot] += self.K
            self._last[slot] = toks[slot, -1]
            for j in range(self.K):
                tok = int(toks[slot, j])
                self.stats["tokens_generated"] += 1
                self.stats["active_slot_steps"] += 1
                st._emit(tok, float(lps[slot, j]))
                self._post_emit(slot, tok)
                if self._slots[slot] is None:
                    break  # EOS/length mid-block: drop the tail

    def _drain_inflight(self):
        while self._inflight:
            self._process_block(*self._inflight.popleft())

    def _sync_host_state(self):
        """Drain the pipeline and pull the device decode state back into
        the host mirrors so admissions (which write per-slot host state)
        operate on current values."""
        self._drain_inflight()
        if self._dev_state is not None:
            _last_d, _pos_d, keys_d = self._dev_state
            # last/pos mirrors were advanced per processed block; only
            # keys (folded on-device every step) need the fetch
            self._keys = np.array(keys_d)
            self._dev_state = None

    def _recover(self, e) -> None:
        """Device failure: salvage what the chip already computed (a
        best-effort drain — those tokens were generated), then fail every
        in-flight stream and any half-ingested prompt, rebuild the
        (possibly donated-away) cache, and keep serving."""
        log.error("serving: dispatch failed: %s", e)
        try:
            self._drain_inflight()
        except Exception:  # noqa: BLE001 — wedged device: drop the rest
            self._inflight.clear()
        self._dev_state = None
        if self._partial is not None:
            self._partial[0].stream._finish(f"error: {e}")
            self._partial = None
        for slot in range(self.B):
            st = self._slots[slot]
            if st is self._RESERVED:
                self._slots[slot] = None
            elif st is not None:
                st._finish(f"error: {e}")
                self._slots[slot] = None
        if self.paged:
            for state in list(self._sstate.values()):
                state["stream"]._finish(f"error: {e}")
            self._sstate.clear()
            if self._held is not None:
                self._held.stream._finish(f"error: {e}")
                self._held = None
            self._lane = [None] * self.B
            # the arena may hold donated-away/error buffers; a fresh one
            # is the same bytes, so accounting is unchanged. Paged prefix
            # entries hold block ids into the dead allocation map — drop
            # them with it.
            self._pool.reset()
            self._bt[:] = self._pool.SENTINEL
            self._prefix.clear()
            self._prefix_trie = _PrefixTrie()
        else:
            self._cache = self._init_cache()
        if self._spec is not None:
            self._spec["dcache"] = None
            self._spec["dcache"] = self._spec["init_dcache"]()

    # -- speculative decoding (speculate=K) -----------------------------------
    def set_speculate(self, k: int,
                      draft_layers: Optional[int] = None) -> None:
        """Reconfigure speculative decoding (the ``speculate=K`` knob on
        tensor_lm_serve). No-op when unchanged; requires a stopped
        engine loop — the draft cache and jitted round program are
        rebuilt. ``k=0`` disables."""
        k = int(k or 0)
        if k == self.speculate and (
                k == 0 or draft_layers == self._speculate_layers):
            return
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "serving: set_speculate requires a stopped engine loop")
        if k < 0:
            raise ValueError(f"serving: speculate must be >= 0, got {k}")
        if k >= self.S:
            raise ValueError(
                f"serving: speculate ({k}) must be < max_seq ({self.S})")
        self.speculate = k
        self._speculate_layers = draft_layers
        self._spec = None
        if k:
            self._build_speculative()

    def _build_speculative(self) -> None:
        """One jitted program per round: γ greedy draft steps (a
        ``draft_layers``-deep prefix slice of the target,
        models/speculative.py), then the target VERIFIES all γ+1
        positions in a single chunk pass — per-row argmax match gives
        n_emit ∈ [1, γ+1] tokens whose values are exactly what
        non-speculative greedy decoding would emit (the target argmax
        is ground truth; drafts only decide how many positions one
        round advances). A rejected draft costs nothing to undo: the
        host simply advances pos by n_emit, and the stale cache slots
        above it are overwritten before they are ever attended (the
        next round's chunk covers them). In paged mode the roll-back
        is the block-table tail pointer — no block copies."""
        if self.temperature > 0:
            raise ValueError(
                "serving: speculate requires greedy decoding "
                "(temperature=0) — draft/verify parity is exact only "
                "for argmax")
        if self._mesh is not None:
            raise ValueError(
                "serving: speculate does not compose with mesh= (the "
                "draft cache is slot-structured, not sharded)")
        jax, jnp = self._jax, self._jnp
        from nnstreamer_tpu.models.speculative import draft_from_target
        from nnstreamer_tpu.models.transformer import (
            build_decode_step,
            build_prefill,
            init_cache,
        )

        cfg = self.cfg
        nl = self._speculate_layers or max(1, cfg.n_layers // 2)
        dcfg, dparams = draft_from_target(cfg, self.params, nl)
        draft_decode = build_decode_step(dcfg, self.S)
        g = self.speculate

        def init_dcache():
            return init_cache(dcfg, self.B, self.S)

        def draft_and_verify(params, dparams, token, dcache, pos,
                             verify):
            """Shared skeleton; ``verify(chunk_toks)`` runs the target
            chunk and returns [b, γ+1, V] logits."""

            def dbody(carry, _):
                tok, dc, p = carry
                lg, dc = draft_decode(dparams, tok, dc, p)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (nxt, dc, p + 1), nxt

            (_tok, dcache, _p), drafts = jax.lax.scan(
                dbody, (token, dcache, pos), None, length=g)
            drafts = jnp.transpose(drafts)                 # [b, γ]
            chunk_toks = jnp.concatenate([token[:, None], drafts],
                                         axis=1)           # [b, γ+1]
            logits = verify(chunk_toks)
            tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lps = jnp.take_along_axis(
                jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
                tgt[..., None], axis=-1)[..., 0]
            match = (tgt[:, :g] == drafts).astype(jnp.int32)
            n_emit = jnp.sum(jnp.cumprod(match, axis=1), axis=1) + 1
            # draft-cache catch-up: (re)write the kv of the LAST emitted
            # token at its position. For m <= γ it is an idempotent
            # rewrite; for a full accept (m = γ+1) it fills the one
            # position the draft scan never wrote, keeping the draft
            # cache canonical (this affects acceptance rate only —
            # correctness is the target's verify either way)
            fix = jnp.where(
                n_emit == 1, token,
                jnp.take_along_axis(
                    tgt, jnp.maximum(n_emit - 2, 0)[:, None], 1)[:, 0])
            _lg, dcache = draft_decode(dparams, fix, dcache,
                                       pos + n_emit - 1)
            return tgt, lps, n_emit, dcache

        if self.paged:
            pchunk = self._paged_chunk_fn

            def spec_round(params, dparams, token, arena, bt, dcache,
                           pos):
                out_box = []  # closure cell for the updated arena tree

                def verify(chunk_toks):
                    b = chunk_toks.shape[0]
                    logits, new_arena = pchunk(
                        params, chunk_toks, arena, bt, pos,
                        jnp.full((b,), g + 1, jnp.int32))
                    out_box.append(new_arena)
                    return logits

                tgt, lps, n_emit, dcache = draft_and_verify(
                    params, dparams, token, dcache, pos, verify)
                return tgt, lps, n_emit, out_box[0], dcache

            dispatch = jax.jit(spec_round, donate_argnums=(3, 5))
        else:
            chunk = self._chunk_fn

            def spec_round(params, dparams, token, cache, dcache, pos):
                out_cache = []

                def verify(chunk_toks):
                    logits, new_cache = chunk(params, chunk_toks, cache,
                                              pos)
                    out_cache.append(new_cache)
                    return logits

                tgt, lps, n_emit, dcache = draft_and_verify(
                    params, dparams, token, dcache, pos, verify)
                return tgt, lps, n_emit, out_cache[0], dcache

            dispatch = jax.jit(spec_round, donate_argnums=(3, 4))
        self._spec = {
            "dparams": dparams, "dcfg": dcfg,
            "dcache": init_dcache(), "init_dcache": init_dcache,
            "prefill": self._jax.jit(build_prefill(dcfg, self.S)),
            "dispatch": dispatch,
        }

    def _draft_prefill(self, req: _PendingRequest, slot: int) -> None:
        jnp = self._jnp
        sp = self._spec
        n = req.prompt.size
        bucket = self._bucket(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = req.prompt
        _lg, dcache1 = sp["prefill"](sp["dparams"], jnp.asarray(padded),
                                     lengths=jnp.asarray([n], jnp.int32))
        sp["dcache"] = self._insert(sp["dcache"], dcache1, slot)

    def _spec_step_mono(self) -> None:
        jnp = self._jnp
        sp = self._spec
        g = self.speculate
        snapshot = [(slot, st) for slot, st in enumerate(self._slots)
                    if st is not None and st is not self._RESERVED]
        if not snapshot:
            return
        t0 = _time.monotonic()
        tgt, lps, n_emit, cache, dcache = sp["dispatch"](
            self.params, sp["dparams"], jnp.asarray(self._last),
            self._cache, sp["dcache"], jnp.asarray(self._pos))
        self._cache = cache
        sp["dcache"] = dcache
        tgt = np.asarray(tgt)
        lps = np.asarray(lps)
        n_emit = np.asarray(n_emit)
        dt = _time.monotonic() - t0
        self.invoke_stats.record(dt)
        self.stats["dispatches"] += 1
        self.stats["slot_steps"] += self.B * (g + 1)
        for slot, st in snapshot:
            if self._slots[slot] is not st:
                continue
            m = int(n_emit[slot])
            self.stats["spec_drafted"] += g
            self.stats["spec_accepted"] += m - 1
            self._pos[slot] += m
            self._last[slot] = int(tgt[slot, m - 1])
            self._lm_stats.observe_token(dt / max(1, m))
            for j in range(m):
                tok = int(tgt[slot, j])
                self.stats["tokens_generated"] += 1
                self.stats["active_slot_steps"] += 1
                st._emit(tok, float(lps[slot, j]))
                self._post_emit(slot, tok)
                if self._slots[slot] is None:
                    break

    def _spec_step_paged(self) -> None:
        jnp = self._jnp
        sp = self._spec
        g = self.speculate
        run = []
        for st in list(self._sstate.values()):
            if self._sstate.get(st["sid"]) is not st:
                continue
            if not self._topup(st):
                continue
            slot = st["slot"]
            self._bt[slot, :] = self._pool.SENTINEL
            self._bt[slot, :len(st["blocks"])] = st["blocks"]
            run.append(st)
        if not run:
            return
        last = np.zeros(self.B, np.int32)
        pos = np.zeros(self.B, np.int32)
        for st in run:
            last[st["slot"]] = st["last"]
            pos[st["slot"]] = st["pos"]
        t0 = _time.monotonic()
        tgt, lps, n_emit, arena, dcache = sp["dispatch"](
            self.params, sp["dparams"], jnp.asarray(last),
            self._pool.arena, jnp.asarray(self._bt), sp["dcache"],
            jnp.asarray(pos))
        self._pool.arena = arena
        sp["dcache"] = dcache
        tgt = np.asarray(tgt)
        lps = np.asarray(lps)
        n_emit = np.asarray(n_emit)
        dt = _time.monotonic() - t0
        self.invoke_stats.record(dt)
        self.stats["dispatches"] += 1
        self.stats["slot_steps"] += self.B * (g + 1)
        for st in run:
            if self._sstate.get(st["sid"]) is not st:
                continue
            slot = st["slot"]
            m = int(n_emit[slot])
            self.stats["spec_drafted"] += g
            self.stats["spec_accepted"] += m - 1
            # rejected drafts roll the block-table tail pointer back by
            # construction: pos advances only m, and the stale kv above
            # it is overwritten before it is ever attended
            st["pos"] += m
            st["last"] = int(tgt[slot, m - 1])
            self._lm_stats.observe_token(dt / max(1, m))
            for j in range(m):
                tok = int(tgt[slot, j])
                self.stats["tokens_generated"] += 1
                self.stats["active_slot_steps"] += 1
                st["stream"]._emit(tok, float(lps[slot, j]))
                self._post_emit_paged(st, tok)
                if self._sstate.get(st["sid"]) is not st:
                    break

    # -- paged mode (block_tokens > 0) ----------------------------------------
    def _blocks_for(self, n: int) -> int:
        """Blocks a fresh n-token-prompt stream needs up front: the
        prompt's positions plus the first decode write (always
        n//T + 1 — the tail block doubles as the decode block unless
        the prompt ends exactly on a boundary)."""
        return n // self.block_tokens + 1

    def _alloc_blocks(self, k: int):
        """Pool alloc with the evict rung of the pressure ladder: LRU
        paged prefix entries are dropped until the allocation fits (or
        nothing is left to drop — the caller then defers or sheds)."""
        ids = self._pool.alloc(k)
        while ids is None and self._evict_prefix_paged():
            ids = self._pool.alloc(k)
        return ids

    def _evict_prefix_paged(self) -> bool:
        if not self._prefix:
            return False
        from nnstreamer_tpu.tensors import memory as _memory

        evicted, (ids, _logits) = self._prefix.popitem(last=False)
        self._prefix_trie.remove(evicted)
        self._pool.release(list(ids))
        acct = _memory.ACTIVE
        if acct is not None:
            acct.count_pressure("evict")
        return True

    def _prefix_lookup_paged(self, prompt: np.ndarray):
        """→ (lcp, entry key, logits). Longest common prefix between
        ``prompt`` and a cached entry; logits only on an exact
        whole-prompt == whole-key hit. Reuse happens at BLOCK
        granularity (the caller rounds down)."""
        if not self.prefix_cache:
            return 0, None, None
        best_key, lcp = self._prefix_trie.lookup(prompt)
        if best_key is None or lcp <= 0:
            return 0, None, None
        self._prefix.move_to_end(best_key)
        _ids, logits = self._prefix[best_key]
        if not (lcp == prompt.size == len(best_key)):
            logits = None
        return lcp, best_key, logits

    def _prefix_store_paged(self, prompt: np.ndarray, blocks,
                            logits) -> None:
        """Retain the stream's prompt-covering blocks as a cache entry:
        sharing is a refcount bump, so a prefix costs its blocks ONCE
        and reuse is exact by construction (same physical kv). The tail
        block may be partial; every reader takes a COW copy of it, and
        the donor stream's later appends land at offsets >= n % T —
        outside the entry's [0, n) range."""
        if not self.prefix_cache:
            return
        key = tuple(int(t) for t in prompt)
        if key in self._prefix:
            return
        n = prompt.size
        T = self.block_tokens
        ids = tuple(blocks[:(n + T - 1) // T])
        self._pool.retain(ids)
        self._prefix_trie.insert(key)
        self._prefix[key] = (ids, logits)
        self._prefix.move_to_end(key)
        while len(self._prefix) > self.prefix_cache:
            evicted, (eids, _lg) = self._prefix.popitem(last=False)
            self._prefix_trie.remove(evicted)
            self._pool.release(list(eids))

    def _admit_paged(self, req: _PendingRequest):
        """Paged admission: allocate the stream's block table, prefill
        cold / block-aligned warm / exact-hit, and return the
        activation record — or None to DEFER when the pool cannot
        cover the prompt (admission is bounded by FREE BLOCKS, not
        batch slots; the caller holds the request so FIFO order keeps).
        Deferral is cheap: every path allocates before device work."""
        self._m_queue_wait.observe(_time.monotonic() - req.submit_t)
        jnp = self._jnp
        prompt = req.prompt
        n = prompt.size
        T = self.block_tokens
        p, key_hit, cached_logits = self._prefix_lookup_paged(prompt)
        if cached_logits is not None:  # exact whole-prompt hit
            eids, _lg = self._prefix[key_hit]
            fresh = self._alloc_blocks(1)
            if fresh is None:
                return None
            full = n // T
            shared = list(eids[:full])
            self._pool.retain(shared)
            blocks = shared + fresh
            try:
                if n % T:
                    # COW fault: private copy of the entry's partial
                    # tail — the stream appends there from offset n % T
                    self._pool.copy_block(eids[full], fresh[0])
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_reused"] += n
                return self._activate_begin_paged(req, cached_logits,
                                                  blocks)
            except Exception:
                self._pool.release(blocks)
                raise
        q = min((p // T) * T, ((n - 1) // T) * T)  # block-aligned reuse
        if (key_hit is not None
                and q >= max(T, self.PREFIX_MIN_REUSE)
                and q + self._bucket(n - q) <= self.S):
            eids, _lg = self._prefix[key_hit]
            shared = list(eids[:q // T])
            fresh = self._alloc_blocks(self._blocks_for(n) - len(shared))
            if fresh is None:
                return None
            self._pool.retain(shared)
            blocks = shared + fresh
            try:
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_reused"] += q
                rem = n - q
                c = self._bucket(rem)
                toks = np.zeros((1, c), np.int32)
                toks[0, :rem] = prompt[q:]
                bt = np.full((1, self.MB), self._pool.SENTINEL, np.int32)
                bt[0, :len(blocks)] = blocks
                logits, arena = self._paged_chunk_jitted(
                    self.params, jnp.asarray(toks), self._pool.arena,
                    jnp.asarray(bt), jnp.asarray([q], jnp.int32),
                    jnp.asarray([rem], jnp.int32))
                self._pool.arena = arena
                logits = logits[:, rem - 1]
                self._prefix_store_paged(prompt, blocks, logits)
                return self._activate_begin_paged(req, logits, blocks)
            except Exception:
                self._pool.release(blocks)
                raise
        blocks = self._alloc_blocks(self._blocks_for(n))
        if blocks is None:
            return None
        try:
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = prompt
            logits, cache1 = self._prefill_jitted(
                self.params, jnp.asarray(padded),
                lengths=jnp.asarray([n], jnp.int32))
            self._pool.scatter_prefill(cache1, blocks[:(n + T - 1) // T])
            self._prefix_store_paged(prompt, blocks, logits)
            return self._activate_begin_paged(req, logits, blocks)
        except Exception:
            self._pool.release(blocks)
            raise

    def _activate_paged_from_cache1(self, req: _PendingRequest, logits,
                                    cache1):
        """Chunked-prefill commit: scatter the finished batch-1 cache
        into fresh blocks. None = pool exhausted (caller re-holds)."""
        n = req.prompt.size
        T = self.block_tokens
        blocks = self._alloc_blocks(self._blocks_for(n))
        if blocks is None:
            return None
        try:
            self._pool.scatter_prefill(cache1, blocks[:(n + T - 1) // T])
            self._prefix_store_paged(req.prompt, blocks, logits)
            return self._activate_begin_paged(req, logits, blocks)
        except Exception:
            self._pool.release(blocks)
            raise

    def _begin_partial_paged(self, req: _PendingRequest) -> None:
        """Chunked prompt ingestion, paged flavor: chunks build a
        batch-1 monolithic cache that the FINAL chunk scatters into
        fresh blocks — no slot is reserved, blocks allocate at
        activation. (Prefix reuse is not wired on this path; chunked
        paged prompts ingest from 0.)"""
        self._m_queue_wait.observe(_time.monotonic() - req.submit_t)
        self._partial = (req, None, self._init_cache1(), 0, 0)

    def _activate_begin_paged(self, req: _PendingRequest, logits, blocks):
        """Paged twin of _activate_begin: sample the first token,
        create the stream's decode state. No lane is claimed (EDF
        binds lanes per dispatch) — except in speculative mode, where
        the slot-structured draft cache pins each stream to a lane for
        life."""
        jnp = self._jnp
        stream = req.stream
        sid = stream.stream_id
        key = np.asarray([self.seed & 0xFFFFFFFF, sid & 0xFFFFFFFF],
                         np.uint32)[None]
        first_d, key_d, lp_d = self._sample_first(logits,
                                                  jnp.asarray(key))
        n = req.prompt.size
        now = _time.monotonic()
        slo_s = self._slo.budget_s if self._slo is not None else 60.0
        state = {
            "sid": sid, "stream": stream, "blocks": list(blocks),
            "pos": n, "last": 0, "key": np.zeros(2, np.uint32),
            # cap writes inside S (a verify chunk writes through pos+K)
            "budget": min(req.max_new, self.S - n - self.speculate),
            #: absolute deadline feeding the per-token EDF key
            "deadline_t": getattr(stream, "submit_t", now) + slo_s,
            "slot": None,
        }
        self._sstate[sid] = state
        if self._spec is not None:
            slot = self._lane.index(None)
            self._lane[slot] = sid
            state["slot"] = slot
            self._draft_prefill(req, slot)
        return (req, state, first_d, key_d, lp_d)

    def _activate_commit_paged(self, rec) -> None:
        req, state, first_d, key_d, lp_d = rec
        self.stats["prefills"] += 1
        first = int(np.asarray(first_d)[0])
        state["last"] = first
        state["key"] = np.asarray(key_d)[0].copy()
        t0 = getattr(req.stream, "submit_t", None)
        if t0 is not None:
            self._lm_stats.observe_ttft(_time.monotonic() - t0)
        req.stream._emit(first, float(np.asarray(lp_d)[0]))
        self.stats["tokens_generated"] += 1
        self._post_emit_paged(state, first)

    def _post_emit_paged(self, state, tok: int) -> None:
        state["budget"] -= 1
        done_eos = self.eos_id is not None and tok == self.eos_id
        done = done_eos or state["budget"] <= 0
        if done and self._slo is not None:
            t0 = getattr(state["stream"], "submit_t", None)
            if t0 is not None:
                now = _time.monotonic()
                self._slo.observe_completion(now - t0, now, frames=1)
                self._slo.observe_service(now - t0, frames=1)
        if done_eos:
            self._finish_paged(state, "eos")
        elif state["budget"] <= 0:
            self._finish_paged(state, "length")

    def _finish_paged(self, state, reason: str) -> None:
        """Paged stream teardown: blocks return to the pool BEFORE the
        client wakes (mirroring the mono engine's slot-free-before-
        finish contract, so a caller that observes its stream done also
        observes the capacity released)."""
        self._sstate.pop(state["sid"], None)
        slot = state["slot"]
        if slot is not None:
            self._lane[slot] = None
            self._bt[slot, :] = self._pool.SENTINEL
            state["slot"] = None
        if state["blocks"]:
            self._pool.release(state["blocks"])
            state["blocks"] = []
        state["stream"]._finish(reason)

    def _shed_one(self, keep_sid: int) -> bool:
        """Decode-time block exhaustion: revoke the MOST-LATE admitted
        stream's blocks (deepest past deadline), replaying the
        admission-revocation accounting — pressure rung "shed", the
        SLO scheduler's shed counters, finish reason "shed". False =
        the only candidate was ``keep_sid`` itself (the caller gives
        that stream up — self-shed)."""
        from nnstreamer_tpu.tensors import memory as _memory

        cands = [st for st in self._sstate.values()
                 if st["sid"] != keep_sid]
        self_shed = not cands
        if self_shed:
            victim = self._sstate.get(keep_sid)
            if victim is None:
                return False
        else:
            victim = min(cands, key=lambda st: st["deadline_t"])
        now = _time.monotonic()
        late = victim["deadline_t"] <= now
        acct = _memory.ACTIVE
        if acct is not None:
            acct.count_pressure("shed")
        if self._slo is not None:
            self._slo.note_shed_request(now, late)
        self.stats["kv_sheds"] += 1
        log.warning("serving: paged KV exhausted — shedding stream %d "
                    "(%s)", victim["sid"], "late" if late else "capacity")
        self._finish_paged(victim, "shed")
        return not self_shed

    def _topup(self, state) -> bool:
        """Grow ``state``'s block table to cover the whole next
        dispatch block (pos+K-1; pos+K for a speculative verify),
        walking the evict → shed ladder on exhaustion. False = the
        stream itself was shed."""
        steps = (self.speculate + 1) if self._spec is not None else self.K
        hi = (state["pos"] + steps - 1) // self.block_tokens
        while len(state["blocks"]) <= hi:
            ids = self._alloc_blocks(hi + 1 - len(state["blocks"]))
            if ids is None:
                if not self._shed_one(state["sid"]):
                    return False
                continue
            state["blocks"].extend(ids)
        return True

    def _decode_step_paged(self) -> None:
        """One EDF-scheduled K-step decode block: bind the B most
        urgent streams (per-TOKEN deadline — a nearly-late short
        stream preempts a long one at block granularity), top up their
        block tables, run the ONE jitted paged program, emit."""
        jnp = self._jnp
        from nnstreamer_tpu.serving.scheduler import token_deadline

        now = _time.monotonic()
        states = list(self._sstate.values())
        if len(states) > self.B:
            states.sort(key=lambda st: token_deadline(
                now, st["deadline_t"], st["budget"]))
            selected = states[:self.B]
            keep = {st["sid"] for st in selected}
            # park preempted streams' lanes (their kv lives in the
            # arena; state re-binds whenever EDF selects them again)
            for slot, sid in enumerate(self._lane):
                if sid is not None and sid not in keep:
                    parked = self._sstate.get(sid)
                    if parked is not None:
                        parked["slot"] = None
                    self._lane[slot] = None
                    self._bt[slot, :] = self._pool.SENTINEL
        else:
            selected = states
        run = []
        for st in selected:
            if self._sstate.get(st["sid"]) is not st:
                continue  # shed while topping up an earlier stream
            if not self._topup(st):
                continue  # self-shed
            if st["slot"] is None:
                slot = self._lane.index(None)
                self._lane[slot] = st["sid"]
                st["slot"] = slot
            slot = st["slot"]
            self._bt[slot, :] = self._pool.SENTINEL
            self._bt[slot, :len(st["blocks"])] = st["blocks"]
            run.append(st)
        if not run:
            return
        last = np.zeros(self.B, np.int32)
        pos = np.zeros(self.B, np.int32)
        keys = np.zeros((self.B, 2), np.uint32)
        for st in run:
            last[st["slot"]] = st["last"]
            pos[st["slot"]] = st["pos"]
            keys[st["slot"]] = st["key"]
        t0 = _time.monotonic()
        toks, lps, arena, keys_d, _last_d, _pos_d = self._dispatch(
            self.params, jnp.asarray(last), self._pool.arena,
            jnp.asarray(self._bt), jnp.asarray(pos), jnp.asarray(keys))
        self._pool.arena = arena
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        keys_np = np.asarray(keys_d)
        dt = _time.monotonic() - t0
        self.invoke_stats.record(dt)
        self.stats["dispatches"] += 1
        self.stats["slot_steps"] += self.B * self.K
        per_tok = dt / self.K
        for st in run:
            if self._sstate.get(st["sid"]) is not st:
                continue
            slot = st["slot"]
            st["key"] = keys_np[slot].copy()
            st["pos"] += self.K
            st["last"] = int(toks[slot, -1])
            self._lm_stats.observe_token(per_tok)
            for j in range(self.K):
                tok = int(toks[slot, j])
                self.stats["tokens_generated"] += 1
                self.stats["active_slot_steps"] += 1
                st["stream"]._emit(tok, float(lps[slot, j]))
                self._post_emit_paged(st, tok)
                if self._sstate.get(st["sid"]) is not st:
                    break  # EOS/length/shed mid-block: drop the tail

    def _loop_paged(self):
        """Paged engine loop. Dispatch → emit runs synchronously (the
        host state it re-uploads per block is a few hundred int32s —
        noise next to the gather the decode already pays), which keeps
        lane parking/rebinding and EDF preemption a plain host-side
        concern instead of a device-state pipeline hazard."""
        while not self._stop_evt.is_set():
            self._reap_condemned()
            for state in list(self._sstate.values()):
                if state["stream"].cancelled:
                    self._finish_paged(state, "cancelled")
            if self._held is not None and self._held.stream.cancelled:
                self._held.stream._finish("cancelled")
                self._held = None
            progressed = False
            if self._partial is not None:
                if self._partial[0].stream.cancelled:
                    self._partial[0].stream._finish("cancelled")
                    self._partial = None
                else:
                    self._advance_partial()
                    progressed = True
            admitted = []
            while self._partial is None:
                if self._spec is not None and \
                        len(self._sstate) >= self.B:
                    break  # slot-structured draft cache caps streams
                if self._held is not None:
                    req, self._held = self._held, None
                else:
                    try:
                        req = self._pending.get_nowait()
                    except _queue.Empty:
                        break
                if req.stream.cancelled:
                    req.stream._finish("cancelled")
                    continue
                try:
                    if self.prefill_chunk is not None:
                        self._begin_partial_paged(req)
                        progressed = True
                        break
                    rec = self._admit_paged(req)
                except Exception as e:  # noqa: BLE001 — a bad request
                    # must not kill the engine loop
                    log.warning("serving: admit failed: %s", e)
                    req.stream._finish(f"error: {e}")
                    continue
                if rec is None:
                    # pool can't cover this prompt yet: hold the head
                    # (completions free blocks; FIFO order preserved)
                    self.stats["kv_defers"] += 1
                    self._held = req
                    break
                admitted.append(rec)
                progressed = True
            for rec in admitted:  # start all fetches before blocking
                for d in (rec[2], rec[3], rec[4]):
                    start_async = getattr(d, "copy_to_host_async", None)
                    if start_async is not None:
                        start_async()
            for rec in admitted:
                try:
                    self._activate_commit_paged(rec)
                except Exception as e:  # noqa: BLE001 — fail only this
                    # stream
                    log.warning("serving: activate failed: %s", e)
                    state = rec[1]
                    if self._sstate.get(state["sid"]) is state:
                        self._finish_paged(state, f"error: {e}")
                    else:
                        rec[0].stream._finish(f"error: {e}")
            if len(self._sstate) > self.stats["concurrent_streams_max"]:
                self.stats["concurrent_streams_max"] = len(self._sstate)
            if not self._sstate:
                if not progressed:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                if self._spec is not None:
                    self._spec_step_paged()
                else:
                    self._decode_step_paged()
            except Exception as e:  # noqa: BLE001 — a device failure
                # must not strand clients blocked on their streams
                self._recover(e)

    def _loop(self):
        if self.paged:
            return self._loop_paged()
        return self._loop_mono()

    def _loop_mono(self):
        jnp = self._jnp
        while not self._stop_evt.is_set():
            self._reap_condemned()
            # honor cancellations first: active slots free at this block
            # boundary; a half-ingested prompt stops mid-prefill
            for slot in range(self.B):
                st = self._slots[slot]
                if (st is not None and st is not self._RESERVED
                        and st.cancelled):
                    self._slots[slot] = None
                    st._finish("cancelled")
            if self._partial is not None and self._partial[0].stream.cancelled:
                _, slot, _, _, _ = self._partial
                self._slots[slot] = None
                self._partial[0].stream._finish("cancelled")
                self._partial = None
            # in-flight chunked prefill: ONE chunk per iteration, so the
            # decode dispatch below keeps running streams moving while a
            # long prompt ingests.
            # (A dispatch-FIRST reordering — decode block issued before
            # admissions so its compute "overlaps" the admission's host
            # work — was tried and reverted: the chip executes queued
            # programs serially, so it bought no measured throughput and
            # cost new streams up to a full K-step block of
            # time-to-first-token, since the wave commit then had to
            # drain a block issued microseconds earlier instead of one
            # nearly done from the previous iteration.)
            progressed = False
            if self._partial is not None:
                self._advance_partial()
                progressed = True
            # admission: fill free slots from the pending queue. The
            # device work (prefill + first-token sample) dispatches per
            # request; the host fetches commit as ONE grouped wave below,
            # so a burst of N prompts costs ~1 link round trip, not N.
            queue_dry = False
            admitted = []
            for slot in range(self.B):
                if queue_dry or self._slots[slot] is not None \
                        or self._partial is not None:
                    continue
                # retry THIS slot past cancelled/failed queue heads — a
                # cancelled request must not cost a slot its admission
                while True:
                    try:
                        req = self._pending.get_nowait()
                    except _queue.Empty:
                        queue_dry = True
                        break
                    if req.stream.cancelled:
                        req.stream._finish("cancelled")
                        continue
                    try:
                        if self.prefill_chunk is not None:
                            self._begin_partial(req, slot)
                        else:
                            admitted.append(self._admit(req, slot))
                        progressed = True
                        break  # slot filled
                    except Exception as e:  # noqa: BLE001 — a bad request
                        # (or a prefill/cache-alloc failure) must not kill
                        # the engine loop
                        log.warning("serving: admit failed: %s", e)
                        if self._slots[slot] is self._RESERVED:
                            self._slots[slot] = None
                        self._partial = None
                        req.stream._finish(f"error: {e}")
            if admitted:
                try:
                    self._sync_host_state()
                except Exception as e:  # noqa: BLE001 — deferred device
                    # errors surface at the drain. _recover already
                    # failed every admitted stream and freed the slots:
                    # committing the wave now would write mirrors into
                    # freed slots and emit ghost tokens
                    self._recover(e)
                    admitted = []
                for rec in admitted:  # start all fetches before blocking
                    for d in (rec[2], rec[3], rec[4]):
                        start_async = getattr(d, "copy_to_host_async",
                                              None)
                        if start_async is not None:
                            start_async()
                for rec in admitted:
                    try:
                        self._activate_commit(rec)
                    except Exception as e:  # noqa: BLE001 — fail only
                        # this stream; the slot frees for the next prompt
                        log.warning("serving: activate failed: %s", e)
                        self._slots[rec[1]] = None
                        rec[0].stream._finish(f"error: {e}")
            if self.active_streams == 0:
                try:
                    self._sync_host_state()  # late EOS frees the last slot
                except Exception as e:  # noqa: BLE001 — deferred device
                    # errors surface at materialization; must not kill the
                    # engine thread
                    self._recover(e)
                    continue
                if self.active_streams == 0:
                    if not progressed:
                        self._wake.wait(timeout=0.05)
                        self._wake.clear()
                    continue
            if self._spec is not None:
                # speculative rounds replace the K-step dispatch; they
                # run synchronously off the host mirrors (variable
                # per-stream emit counts don't pipeline)
                try:
                    self._sync_host_state()
                    self._spec_step_mono()
                except Exception as e:  # noqa: BLE001
                    self._recover(e)
                continue
            try:
                t0 = _time.monotonic()
                if self._dev_state is None:
                    last_d = jnp.asarray(self._last)
                    pos_d = jnp.asarray(self._pos)
                    keys_d = jnp.asarray(self._keys)
                else:
                    last_d, pos_d, keys_d = self._dev_state
                toks, lps, self._cache, keys_d, last_d, pos_d = \
                    self._dispatch(self.params, last_d, self._cache,
                                   pos_d, keys_d)
                self._dev_state = (last_d, pos_d, keys_d)
                # start the transfers NOW; the blocking materialization
                # runs one block behind, so the link round trip overlaps
                # the next dispatch's compute instead of serializing it
                for t in (toks, lps):
                    start_async = getattr(t, "copy_to_host_async", None)
                    if start_async is not None:
                        start_async()
                self._inflight.append((t0, toks, lps, [
                    (slot, st) for slot, st in enumerate(self._slots)
                    if st is not None and st is not self._RESERVED]))
                if len(self._inflight) > 1:
                    self._process_block(*self._inflight.popleft())
            except Exception as e:  # noqa: BLE001 — a device failure must
                # not strand clients blocked on their streams
                self._recover(e)
                continue
        # stop requested: flush the pipelined blocks so streams whose
        # tokens were already computed still receive them
        try:
            self._drain_inflight()
        except Exception as e:  # noqa: BLE001 — draining on shutdown is
            # best-effort; a dead device must not block stop()
            log.warning("serving: drain at stop failed: %s", e)
