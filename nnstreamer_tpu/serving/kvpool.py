"""Paged KV-cache allocator: one preallocated device arena, block tables.

The monolithic serving cache gives every one of ``max_streams`` batch
slots the full ``max_seq`` window — HBM cost B×S whether streams use it
or not, concurrency hard-capped at B. This module carves the same bytes
into fixed ``block_tokens``-sized blocks instead (the compiler-first
O(1) autoregressive-caching form, PAPERS.md):

- **Arena** — one device pytree per codec, leaves ``[L, NTOT, 2, T, h,
  dh]`` (int8 adds a ``[L, NTOT, 2, T, h]`` scale leaf). The leading L
  axis lets the decode layer scan carry one per-layer block-pool slice,
  exactly like the monolithic cache's leading L. ``NTOT = num_blocks +
  1``: index ``num_blocks`` is a permanent ZERO block that is never
  allocated and never written.
- **Sentinel** — unallocated block-table entries hold ``SENTINEL =
  NTOT``, deliberately out of bounds: gathers clamp onto the zero block
  (reads are exact zeros, finite and masked anyway) and scatters use
  ``mode="drop"`` (writes vanish). One sentinel serves empty batch
  lanes, bucket padding, and not-yet-allocated tail blocks alike.
- **Free list / refcounts** — LIFO free list (hot blocks stay hot in
  whatever cache hierarchy sits under HBM), per-block refcounts so
  copy-on-write prefix sharing is a ``retain``; a block returns to the
  free list when its last owner releases it. Allocation is
  all-or-nothing: a stream that cannot get every block it asked for
  gets none, so the engine's shed ladder sees a clean failure.
- **Accounting** — the arena registers its bytes with the PR-12 HBM
  accountant under the ``kvcache`` category at construction, so cache
  pressure shows up in ``nns_mem_used_bytes{category="kvcache"}`` and
  rides the same evict → shed → cpu ladder as weights and frames.

Model-side consumers (models/transformer.py paged builders) never index
the arena directly — they receive per-layer slices from the scan and a
block table. Direct arena subscripts outside this file are flagged by
lint rule NNS118: every host-side mutation (prefill scatter, COW block
copy) must go through the pool so refcounts, donation, and the zero
block's invariants stay in one place.

Kill switch: ``NNSTPU_PAGED_KV=0`` (or ``block_tokens=0`` on the
engine) disables paging entirely — the engine then never imports an
arena and runs the monolithic PR-18 path byte-identically.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.tensors import memory as _memory

_FALSY = ("0", "false", "no", "off")


def paged_enabled() -> bool:
    """Environment kill switch (default ON; the engine additionally
    requires ``block_tokens > 0``, which defaults off)."""
    return os.environ.get("NNSTPU_PAGED_KV", "1").strip().lower() \
        not in _FALSY


def _scatter_prefill_impl(arena, cache1, bids):
    """Scatter a batch-1 monolithic cache ([L, 2, 1, S, ...] leaves) into
    arena blocks ``bids`` ([S/T] int32, sentinel entries drop). Block i
    receives slots [i*T, (i+1)*T) — including any trailing bucket-pad
    garbage in the last data block, which stays masked until the owning
    stream overwrites it (the same padded-prefill contract as the
    monolithic cache)."""
    import jax
    import jax.numpy as jnp

    def leaf(a, c):
        L = c.shape[0]
        S = c.shape[3]
        T = a.shape[3]
        u = c[:, :, 0]                                   # [L,2,S,...]
        u = u.reshape((L, 2, S // T, T) + u.shape[3:])
        u = jnp.moveaxis(u, 2, 1)                        # [L,MB,2,T,...]
        return a.at[:, bids].set(u.astype(a.dtype), mode="drop")

    return jax.tree.map(leaf, arena, cache1)


def _copy_block_impl(arena, src, dst):
    """Copy one physical block across every layer/leaf — the COW fault
    path when a stream extends a shared prefix whose tail block is only
    partially full."""
    import jax

    return jax.tree.map(lambda a: a.at[:, dst].set(a[:, src]), arena)


class BlockPool:
    """Allocator + device arena for one engine's paged KV cache.

    Host-side state (free list, refcounts) is guarded by a lock so the
    engine thread and observers can touch it concurrently; device state
    (``self.arena``) is owned by the engine loop, which threads it
    through jitted programs with donation and writes the result back.
    """

    def __init__(self, cfg, num_blocks: int, block_tokens: int,
                 kv_codec: Optional[str] = None, mesh=None,
                 owner: str = "kvpool"):
        from nnstreamer_tpu.models.transformer import _kv_codec

        if num_blocks <= 0:
            raise ValueError(f"BlockPool: num_blocks must be positive, "
                             f"got {num_blocks}")
        if block_tokens <= 0:
            raise ValueError(f"BlockPool: block_tokens must be positive, "
                             f"got {block_tokens}")
        self.cfg = cfg
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.ntot = self.num_blocks + 1       # + the permanent zero block
        self.SENTINEL = self.ntot             # out of bounds on purpose
        self.kv_codec = kv_codec
        self.mesh = mesh
        self.owner = owner
        self._codec = _kv_codec(cfg, kv_codec)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.num_blocks))
        self._ref = np.zeros(self.num_blocks, np.int64)
        self.arena = self._make_arena()

        import jax
        leaves = jax.tree_util.tree_leaves(self.arena)
        self.nbytes = int(sum(l.nbytes for l in leaves))
        self._jit_scatter = jax.jit(_scatter_prefill_impl,
                                    donate_argnums=(0,))
        self._jit_copy = jax.jit(_copy_block_impl, donate_argnums=(0,))

        acct = _memory.ACTIVE
        if acct is not None:
            acct.register(self.nbytes, "kvcache")
            self._acct_finalizer = weakref.finalize(
                self, _unregister_arena, weakref.ref(acct), self.nbytes)
        else:
            self._acct_finalizer = None

    # -- arena construction -------------------------------------------

    def _make_arena(self):
        cfg = self.cfg
        arena = self._codec.paged_init(cfg.n_layers, self.ntot,
                                       self.block_tokens, cfg.n_heads,
                                       cfg.head_dim)
        if self.mesh is not None:
            arena = self._place(arena)
        return arena

    def _place(self, arena):
        from jax.sharding import PartitionSpec as P

        from nnstreamer_tpu.parallel import serve as _serve

        names = set(self.mesh.axis_names)
        dp = "dp" if "dp" in names else None
        tp = "tp" if "tp" in names else None
        if dp and self.ntot % self.mesh.shape["dp"]:
            raise ValueError(
                f"BlockPool: arena block count {self.ntot} (incl. zero "
                f"block) must divide over dp={self.mesh.shape['dp']} — "
                f"pad num_blocks")

        def spec_of(leaf):
            # [L, NTOT, 2, T, h(, dh)] — blocks over dp, heads over tp
            head = (None, dp, None, None, tp)
            return P(*(head + (None,) * (leaf.ndim - 5)))

        return _serve.place_tree(arena, self.mesh, spec_of,
                                 label=f"{self.owner}:kvpool")

    # -- host-side bookkeeping ----------------------------------------

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, k: int) -> Optional[List[int]]:
        """All-or-nothing: ``k`` fresh blocks (refcount 1 each) or None."""
        if k <= 0:
            return []
        with self._lock:
            if len(self._free) < k:
                return None
            ids = [self._free.pop() for _ in range(k)]
            for i in ids:
                self._ref[i] = 1
            return ids

    def retain(self, ids: Sequence[int]) -> None:
        with self._lock:
            for i in ids:
                if self._ref[i] <= 0:
                    raise RuntimeError(
                        f"BlockPool.retain: block {i} is not live")
                self._ref[i] += 1

    def release(self, ids: Sequence[int]) -> None:
        with self._lock:
            for i in ids:
                if self._ref[i] <= 0:
                    raise RuntimeError(
                        f"BlockPool.release: block {i} over-released")
                self._ref[i] -= 1
                if self._ref[i] == 0:
                    self._free.append(i)

    def live_blocks(self) -> int:
        with self._lock:
            return int(np.count_nonzero(self._ref))

    # -- device-side helpers ------------------------------------------

    def scatter_prefill(self, cache1, block_ids: Sequence[int]) -> None:
        """Move a batch-1 prefill cache into ``block_ids`` (padded with
        the sentinel up to S/T). Mutates ``self.arena`` in place (the old
        arena buffer is donated)."""
        import jax.numpy as jnp

        mb = _leaf_slots(cache1) // self.block_tokens
        bids = np.full(mb, self.SENTINEL, np.int32)
        bids[:len(block_ids)] = block_ids
        self.arena = self._jit_scatter(self.arena, cache1,
                                       jnp.asarray(bids))

    def copy_block(self, src: int, dst: int) -> None:
        """COW fault: duplicate physical block ``src`` into ``dst``."""
        import jax.numpy as jnp

        self.arena = self._jit_copy(self.arena,
                                    jnp.asarray(src, jnp.int32),
                                    jnp.asarray(dst, jnp.int32))

    def reset(self) -> None:
        """Drop every allocation and rebuild a zeroed arena — the engine
        recovery path (mirrors re-running ``_init_cache`` on the
        monolithic engine). Accounting is unchanged: same bytes."""
        with self._lock:
            self._free = list(range(self.num_blocks))
            self._ref[:] = 0
        self.arena = self._make_arena()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "num_blocks": self.num_blocks,
                "block_tokens": self.block_tokens,
                "free_blocks": len(self._free),
                "live_blocks": int(np.count_nonzero(self._ref)),
                "nbytes": self.nbytes,
            }


def _leaf_slots(cache1) -> int:
    """Sequence length S of a batch-1 monolithic cache pytree."""
    import jax

    return jax.tree_util.tree_leaves(cache1)[0].shape[3]


def _unregister_arena(acct_ref, nbytes):
    acct = acct_ref()
    if acct is not None:
        acct.unregister(nbytes, "kvcache")
