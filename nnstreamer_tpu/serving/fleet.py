"""Replicated serving fleet: N replicas behind one discovery operation.

The reference edge-AI deployment runs ONE ``tensor_query_server`` per
device and leaves replication to the operator (tensor_query_hybrid only
*discovers* whatever happens to be advertised). This module is the
missing operator: ``nns-fleet`` launches and supervises N replica
*processes* — separate interpreters, so N CPU-bound replicas scale past
the GIL — each advertising itself under the same operation with a
refreshed load block. Clients front the fleet with
``tensor_query_client operation=<op> reliable=true
balance=shortest-slack`` (see ``query/balance.py``) and route every
frame to the replica with the shortest expected completion.

Per replica the launcher provides:

- an isolated state dir (``<state>/replica<i>``) holding the resilient
  dedup-window checkpoint a graceful shutdown writes and the next boot
  restores — the exactly-once half of rolling restarts;
- a SHARED compile cache (``<state>/compile-cache`` via
  ``NNSTPU_COMPILE_CACHE``): the first replica pays each XLA
  compilation, siblings and restarts boot warm;
- crash supervision: an exited replica is relaunched with bounded
  exponential backoff (``nns_fleet_restarts_total`` counts, the backoff
  caps at :data:`RESTART_BACKOFF_MAX_S`, and a replica that stays up
  :data:`RESTART_RESET_S` earns its counter back);
- rolling deploys: :meth:`FleetLauncher.rolling_restart` cycles one
  replica at a time through SIGTERM (checkpoint) → respawn (restore) →
  re-advertise, so the fleet never loses more than one replica of
  capacity and in-flight frames ride the client's sticky reconnect.

Two replica flavors: the built-in echo replica (``--replica`` mode of
this module — a resilient ``QueryServer`` whose worker spins for
``--spin-ms`` of CPU then echoes the frame back doubled; the fleet
bench and chaos smoke use it as a deterministic stand-in for a model)
and arbitrary pipelines via ``--desc`` (launched through ``nns-launch``
with per-replica checkpoint dirs; ``{index}`` in the description is
substituted per replica).

Kill switches: no fleet process is ever implied — this module only runs
when invoked. Clients keep their exact single-server path with
``balance=off`` (default) or ``NNSTPU_FLEET=0``.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.log import get_logger

log = get_logger("fleet")

#: crash-restart backoff: base * 2^restarts, capped here (seconds)
RESTART_BACKOFF_BASE_S = 0.5
RESTART_BACKOFF_MAX_S = 10.0
#: a replica up this long gets its restart counter reset — distinguishes
#: a crash loop from the occasional fault
RESTART_RESET_S = 30.0
#: dedup/continuity checkpoint file inside a replica's state dir
CHECKPOINT_FILE = "query_server.pkl"


class ReplicaHandle:
    """One supervised replica process."""

    def __init__(self, index: int, state_dir: Path):
        self.index = index
        self.state_dir = state_dir
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.started_t = 0.0
        self.next_spawn_t = 0.0
        #: set while the launcher itself is taking the replica down
        #: (rolling restart / stop) so the supervisor doesn't race it
        self.expected_exit = False

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def _fleet_metrics():
    from nnstreamer_tpu.obs import get_registry

    reg = get_registry()
    return {
        "up": reg.gauge(
            "nns_fleet_replicas_up",
            "Live replica processes under fleet supervision"),
        "restarts": reg.counter(
            "nns_fleet_restarts_total",
            "Replica processes relaunched after an unexpected exit"),
    }


class FleetLauncher:
    """Spawn and supervise N replicas behind one discovery operation.

    With ``broker_port=0`` the launcher starts its own pub/sub broker
    (the TCP shim — cross-process capable) and replicas/clients are
    pointed at it; pass an existing broker's port to join one. Replica
    ports are ``base_port + index`` when ``base_port`` is set (stable
    endpoints across restarts — what the balancer's sticky reconnect
    wants), else each boot binds an ephemeral port and re-advertises.
    """

    def __init__(self, replicas: int, operation: str = "fleet",
                 broker_host: str = "127.0.0.1", broker_port: int = 0,
                 state_dir: Optional[str] = None, base_port: int = 0,
                 spin_ms: float = 2.0, budget_ms: float = 0.0,
                 advertise_interval_s: float = 0.25,
                 desc: Optional[str] = None, metrics: bool = False,
                 log_invokes: bool = False,
                 env: Optional[Dict[str, str]] = None):
        if replicas < 1:
            raise ValueError("fleet needs at least one replica")
        self.replicas = int(replicas)
        self.operation = operation
        self.broker_host = broker_host
        self.broker_port = int(broker_port)
        self.base_port = int(base_port)
        self.spin_ms = float(spin_ms)
        self.budget_ms = float(budget_ms)
        self.advertise_interval_s = float(advertise_interval_s)
        self.desc = desc
        self.metrics = bool(metrics)
        self.log_invokes = bool(log_invokes)
        self.extra_env = dict(env or {})
        if state_dir:
            self.state_dir = Path(state_dir)
        else:
            import tempfile

            self.state_dir = Path(tempfile.mkdtemp(prefix="nns-fleet-"))
        self._broker = None  # owned Broker when broker_port was 0
        self._handles: List[ReplicaHandle] = []
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._m = _fleet_metrics()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetLauncher":
        if self.broker_port == 0:
            from nnstreamer_tpu.query.pubsub import Broker

            self._broker = Broker(host="127.0.0.1", port=0).start()
            self.broker_host = "127.0.0.1"
            self.broker_port = self._broker.port
            log.info("fleet broker on 127.0.0.1:%d", self.broker_port)
        self.state_dir.mkdir(parents=True, exist_ok=True)
        (self.state_dir / "compile-cache").mkdir(exist_ok=True)
        self._stopping.clear()
        for i in range(self.replicas):
            h = ReplicaHandle(i, self.state_dir / f"replica{i}")
            h.state_dir.mkdir(parents=True, exist_ok=True)
            self._handles.append(h)
            self._spawn(h)
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="fleet-supervisor",
            daemon=True)
        self._supervisor.start()
        return self

    def _replica_cmd(self, h: ReplicaHandle) -> List[str]:
        if self.desc:
            return [sys.executable, "-m", "nnstreamer_tpu.cli",
                    self.desc.replace("{index}", str(h.index)),
                    "--checkpoint-dir", str(h.state_dir)]
        cmd = [sys.executable, "-m", "nnstreamer_tpu.serving.fleet",
               "--replica",
               "--operation", self.operation,
               "--broker-host", self.broker_host,
               "--broker-port", str(self.broker_port),
               "--port", str(self.base_port + h.index
                             if self.base_port else 0),
               "--state-dir", str(h.state_dir),
               "--spin-ms", str(self.spin_ms),
               "--advertise-interval-s", str(self.advertise_interval_s)]
        if self.budget_ms > 0:
            cmd += ["--budget-ms", str(self.budget_ms)]
        if self.metrics:
            cmd += ["--metrics-port", "0"]
        if self.log_invokes:
            cmd += ["--invoke-log", str(h.state_dir / "invokes.log")]
        return cmd

    def _spawn(self, h: ReplicaHandle) -> None:
        env = dict(os.environ)
        env["NNSTPU_COMPILE_CACHE"] = str(self.state_dir / "compile-cache")
        env.update(self.extra_env)
        h.expected_exit = False
        h.started_t = time.monotonic()
        # replica output goes to its state dir, not the launcher's
        # stdout — bench/CI consumers parse the launcher's JSON lines
        with open(h.state_dir / "replica.log", "ab") as out:
            h.proc = subprocess.Popen(self._replica_cmd(h), env=env,
                                      stdout=out,
                                      stderr=subprocess.STDOUT)
        log.info("replica %d spawned (pid %d)", h.index, h.proc.pid)
        self._m["up"].set(self.replicas_up())

    def _supervise_loop(self) -> None:
        while not self._stopping.wait(0.2):
            now = time.monotonic()
            for h in self._handles:
                with self._lock:
                    if h.expected_exit or h.alive():
                        if h.alive() and h.restarts and \
                                now - h.started_t > RESTART_RESET_S:
                            h.restarts = 0
                        continue
                    if h.proc is None:
                        continue
                    if h.next_spawn_t == 0.0:
                        rc = h.proc.returncode
                        h.restarts += 1
                        backoff = min(
                            RESTART_BACKOFF_MAX_S,
                            RESTART_BACKOFF_BASE_S
                            * 2 ** min(h.restarts - 1, 6))
                        h.next_spawn_t = now + backoff
                        self._m["restarts"].inc()
                        self._m["up"].set(self.replicas_up())
                        log.warning(
                            "replica %d exited rc=%s; restart %d in "
                            "%.1fs", h.index, rc, h.restarts, backoff)
                        continue
                    if now >= h.next_spawn_t:
                        h.next_spawn_t = 0.0
                        self._spawn(h)

    def replicas_up(self) -> int:
        return sum(1 for h in self._handles if h.alive())

    # -- discovery-side readiness ------------------------------------------
    def endpoints(self, timeout: float = 10.0,
                  expect: Optional[int] = None
                  ) -> List[Tuple[str, int]]:
        """Wait until ``expect`` (default: all) replicas advertise, and
        return their (host, port) list."""
        from nnstreamer_tpu.query.discovery import ServerDiscovery

        want = self.replicas if expect is None else int(expect)
        disco = ServerDiscovery(self.broker_host, self.broker_port,
                                self.operation)
        try:
            deadline = time.monotonic() + timeout
            while True:
                found = disco.servers_now()
                if len(found) >= want or time.monotonic() > deadline:
                    return sorted(found)
                time.sleep(0.05)
        finally:
            disco.close()

    # -- controlled restarts ------------------------------------------------
    def kill_replica(self, index: int, graceful: bool = True,
                     wait_s: float = 10.0) -> None:
        """Take one replica down (SIGTERM = checkpoint first, SIGKILL =
        crash). The supervisor relaunches it with backoff."""
        h = self._handles[index]
        if not h.alive():
            return
        h.proc.send_signal(signal.SIGTERM if graceful else signal.SIGKILL)
        try:
            h.proc.wait(timeout=wait_s)
        except subprocess.TimeoutExpired:
            h.proc.kill()
            h.proc.wait(timeout=wait_s)
        self._m["up"].set(self.replicas_up())

    def restart_replica(self, index: int, graceful: bool = True,
                        wait_s: float = 10.0) -> None:
        """Deterministic restart (no supervisor backoff): checkpoint →
        kill → respawn → wait for the fresh advertisement."""
        h = self._handles[index]
        with self._lock:
            h.expected_exit = True
        if h.alive():
            h.proc.send_signal(signal.SIGTERM if graceful
                               else signal.SIGKILL)
            try:
                h.proc.wait(timeout=wait_s)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=wait_s)
        with self._lock:
            h.restarts = 0
            h.next_spawn_t = 0.0
            self._spawn(h)
        # back up before a replica counts as deployed: its ad must be
        # re-published (port may have changed when base_port is 0)
        self.endpoints(timeout=wait_s, expect=self.replicas)

    def rolling_restart(self, graceful: bool = True,
                        wait_s: float = 15.0) -> None:
        """Deploy rehearsal: cycle every replica through checkpoint →
        kill → restore, one at a time, never dropping more than one
        replica of capacity."""
        for i in range(self.replicas):
            log.info("rolling restart: replica %d", i)
            self.restart_replica(i, graceful=graceful, wait_s=wait_s)

    def stop(self) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5.0)
            self._supervisor = None
        for h in self._handles:
            h.expected_exit = True
            if h.alive():
                h.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10.0
        for h in self._handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(0.1,
                                        deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=5.0)
        self._m["up"].set(0)
        if self._broker is not None:
            self._broker.stop()
            self._broker = None


# ---------------------------------------------------------------------------
# built-in echo replica (--replica): a resilient QueryServer + CPU spin
# ---------------------------------------------------------------------------
def _replica_main(args: argparse.Namespace,
                  announce: Callable[[str], None]) -> int:
    from nnstreamer_tpu.query.discovery import ServerAdvertiser
    from nnstreamer_tpu.query.server import QueryServer
    from nnstreamer_tpu.tensors.buffer import TensorBuffer

    state_dir = Path(args.state_dir) if args.state_dir else None
    ckpt = state_dir / CHECKPOINT_FILE if state_dir else None

    server = QueryServer(host="127.0.0.1", port=int(args.port),
                         resilient=True).start()
    if ckpt and ckpt.exists():
        try:
            server.restore_state(pickle.loads(ckpt.read_bytes()))
            log.info("replica restored dedup state from %s", ckpt)
        except Exception as e:  # noqa: BLE001 — a bad checkpoint must
            # not keep the replica down; it just boots cold
            log.warning("checkpoint %s unreadable (%s); cold boot",
                        ckpt, e)

    metrics_srv = None
    if args.metrics_port is not None:
        from nnstreamer_tpu.obs.server import MetricsServer

        metrics_srv = MetricsServer(host="127.0.0.1",
                                    port=int(args.metrics_port)).start()

    service_ewma = [max(args.spin_ms, 0.1)]  # ms, seeded with the spin

    def _load() -> dict:
        load = {"queue_depth": int(server.incoming.qsize()),
                "service_ms": round(service_ewma[0], 3)}
        if args.budget_ms > 0:
            load["slack_headroom_ms"] = round(
                args.budget_ms
                - (load["queue_depth"] + 1) * service_ewma[0], 3)
        return load

    advertiser = ServerAdvertiser(
        args.broker_host, int(args.broker_port), args.operation,
        "127.0.0.1", server.port,
        metrics_port=metrics_srv.port if metrics_srv else None,
        load_fn=_load, refresh_s=float(args.advertise_interval_s))
    advertiser.publish()
    # the replica process's one machine-readable stdout line (the
    # launcher's CI smoke parses it); emission goes through the CLI
    # entry point's announce callable, not a library print
    announce(json.dumps({"replica": "up", "port": server.port,
                         "pid": os.getpid()}))

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    invoke_log = open(args.invoke_log, "a") if args.invoke_log else None
    spin_s = max(0.0, float(args.spin_ms)) / 1e3
    try:
        while not stop.is_set():
            buf = server.get_buffer(timeout=0.1)
            if buf is None:
                continue
            t0 = time.monotonic()
            if spin_s:
                # CPU-bound on purpose: fleet scaling must come from
                # real process parallelism, not sleep concurrency
                while time.monotonic() - t0 < spin_s:
                    pass
            out = TensorBuffer([t * 2 for t in buf.to_host().tensors],
                               pts=buf.pts)
            out.meta.update(buf.meta)
            if invoke_log is not None:
                invoke_log.write(
                    f"{buf.meta.get('net_instance', '')}:"
                    f"{buf.meta.get('net_req_id', -1)}\n")
                invoke_log.flush()
            service_ewma[0] += 0.2 * ((time.monotonic() - t0) * 1e3
                                      - service_ewma[0])
            server.send_result(buf.meta.get("query_client_id", 0), out)
    finally:
        if invoke_log is not None:
            invoke_log.close()
        if ckpt:
            # the deploy contract: state lands on disk BEFORE the ad is
            # retracted, so the successor replays instead of re-invoking
            ckpt.write_bytes(pickle.dumps(server.checkpoint_state()))
            log.info("replica checkpointed dedup state to %s", ckpt)
        try:
            advertiser.retract()
        except OSError:
            pass
        server.stop()
        if metrics_srv is not None:
            metrics_srv.stop()
    return 0


# ---------------------------------------------------------------------------
# nns-fleet CLI
# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-fleet",
        description="Launch and supervise a replicated serving fleet "
                    "behind one discovery operation (see "
                    "docs/distributed.md, Replicated fleet).")
    ap.add_argument("-n", "--replicas", type=int, default=2,
                    help="replica process count (default 2)")
    ap.add_argument("--operation", default="fleet",
                    help="discovery operation clients subscribe to")
    ap.add_argument("--broker-host", default="127.0.0.1")
    ap.add_argument("--broker-port", type=int, default=0,
                    help="pub/sub broker port; 0 starts an owned broker "
                         "on a free port (printed at startup)")
    ap.add_argument("--base-port", type=int, default=0,
                    help="replica i serves on base+i (stable endpoints "
                         "across restarts); 0 = ephemeral ports")
    ap.add_argument("--state-dir", default=None,
                    help="fleet state root: per-replica checkpoint dirs "
                         "+ the shared compile cache (default: a fresh "
                         "temp dir)")
    ap.add_argument("--desc", default=None,
                    help="pipeline description to run per replica via "
                         "nns-launch ({index} substituted); default is "
                         "the built-in echo replica")
    ap.add_argument("--spin-ms", type=float, default=2.0,
                    help="echo replica: CPU-bound service time per "
                         "frame (ms)")
    ap.add_argument("--budget-ms", type=float, default=0.0,
                    help="echo replica: SLO budget advertised through "
                         "the ad's slack_headroom_ms")
    ap.add_argument("--advertise-interval-s", type=float, default=0.25,
                    help="discovery-ad refresh cadence carrying the "
                         "live load block")
    ap.add_argument("--metrics", action="store_true",
                    help="give each echo replica a /metrics.json "
                         "server, advertised for fleet federation")
    ap.add_argument("--rolling-restart", action="store_true",
                    help="once all replicas advertise, cycle each "
                         "through checkpoint → kill → restore (deploy "
                         "rehearsal), then keep serving")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="exit after this long (0 = serve until "
                         "SIGINT/SIGTERM)")
    # internal: replica-process mode (spawned by FleetLauncher)
    ap.add_argument("--replica", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--invoke-log", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.replica:
        return _replica_main(args, lambda line: print(line, flush=True))

    fleet = FleetLauncher(
        replicas=args.replicas, operation=args.operation,
        broker_host=args.broker_host, broker_port=args.broker_port,
        state_dir=args.state_dir, base_port=args.base_port,
        spin_ms=args.spin_ms, budget_ms=args.budget_ms,
        advertise_interval_s=args.advertise_interval_s,
        desc=args.desc, metrics=args.metrics).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        eps = fleet.endpoints(timeout=30.0)
        print(json.dumps({
            "fleet": args.operation,
            "broker": f"{fleet.broker_host}:{fleet.broker_port}",
            "replicas": fleet.replicas_up(),
            "endpoints": [f"{h}:{p}" for h, p in eps],
            "state_dir": str(fleet.state_dir),
        }), flush=True)
        if args.rolling_restart:
            fleet.rolling_restart()
            print(json.dumps({"rolling_restart": "done",
                              "replicas": fleet.replicas_up()}),
                  flush=True)
        deadline = (time.monotonic() + args.duration_s
                    if args.duration_s > 0 else None)
        while not stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            stop.wait(0.2)
    finally:
        fleet.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
