"""Continuous-batching LM serving — TPU-native request scheduling.

New capability beyond the reference (whose closest analog is the
tensor_query server's one-buffer-per-client request loop,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_server.c): N
generation streams share ONE batched, KV-cached decode program. Admission
happens at dispatch boundaries; each stream owns a batch slot of the
device-resident cache; the hot loop is a single jitted multi-step decode
whose shapes never change, so XLA compiles it exactly once.
"""

import threading
from typing import Dict, Optional

from nnstreamer_tpu.serving.engine import (
    ContinuousBatchingEngine,
    GenerationStream,
)

#: name → engine, so pipeline elements (tensor_lm_serve) can reference an
#: app-constructed engine by property — the register_jax_model pattern
_ENGINES: Dict[str, ContinuousBatchingEngine] = {}
_ENGINES_LOCK = threading.Lock()


def register_engine(name: str, engine: ContinuousBatchingEngine) -> None:
    with _ENGINES_LOCK:
        _ENGINES[name] = engine


def get_engine(name: str) -> Optional[ContinuousBatchingEngine]:
    with _ENGINES_LOCK:
        return _ENGINES.get(name)


def unregister_engine(name: str) -> bool:
    with _ENGINES_LOCK:
        return _ENGINES.pop(name, None) is not None


__all__ = ["ContinuousBatchingEngine", "GenerationStream",
           "register_engine", "get_engine", "unregister_engine"]
