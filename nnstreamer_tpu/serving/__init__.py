"""Continuous-batching LM serving — TPU-native request scheduling.

New capability beyond the reference (whose closest analog is the
tensor_query server's one-buffer-per-client request loop,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_server.c): N
generation streams share ONE batched, KV-cached decode program. Admission
happens at dispatch boundaries; each stream owns a batch slot of the
device-resident cache; the hot loop is a single jitted multi-step decode
whose shapes never change, so XLA compiles it exactly once.
"""

from nnstreamer_tpu.serving.engine import (
    ContinuousBatchingEngine,
    GenerationStream,
)

__all__ = ["ContinuousBatchingEngine", "GenerationStream"]
