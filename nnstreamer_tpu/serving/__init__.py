"""Continuous-batching LM serving — TPU-native request scheduling.

New capability beyond the reference (whose closest analog is the
tensor_query server's one-buffer-per-client request loop,
/root/reference/gst/nnstreamer/tensor_query/tensor_query_server.c): N
generation streams share ONE batched, KV-cached decode program. Admission
happens at dispatch boundaries; each stream owns a batch slot of the
device-resident cache; the hot loop is a single jitted multi-step decode
whose shapes never change, so XLA compiles it exactly once.

``serving/scheduler.py`` adds the SLO layer shared by the frame pipeline
and this engine: deadline admission control, EDF ordering, late-first
shedding, and a feedback controller over batch-cap/inflight (see
docs/profiling.md, "SLO tuning"). The engine module is imported lazily:
the scheduler attaches to plain frame pipelines that never touch the LM
stack, and must not drag the transformer models in with it.
"""

import threading
from typing import Dict, Optional

from nnstreamer_tpu.serving.scheduler import (
    FeedbackController,
    ServiceRateEstimator,
    SloRejected,
    SloScheduler,
)

#: name → engine, so pipeline elements (tensor_lm_serve) can reference an
#: app-constructed engine by property — the register_jax_model pattern
_ENGINES: Dict[str, "ContinuousBatchingEngine"] = {}
_ENGINES_LOCK = threading.Lock()


def register_engine(name: str, engine) -> None:
    with _ENGINES_LOCK:
        _ENGINES[name] = engine


def get_engine(name: str):
    with _ENGINES_LOCK:
        return _ENGINES.get(name)


def unregister_engine(name: str) -> bool:
    with _ENGINES_LOCK:
        return _ENGINES.pop(name, None) is not None


def __getattr__(name: str):
    # lazy: engine.py pulls the transformer model stack; a frame
    # pipeline that only needs the SLO scheduler must not pay for it
    if name in ("ContinuousBatchingEngine", "GenerationStream"):
        from nnstreamer_tpu.serving import engine as _engine

        return getattr(_engine, name)
    if name == "FleetLauncher":
        from nnstreamer_tpu.serving.fleet import FleetLauncher

        return FleetLauncher
    raise AttributeError(name)


__all__ = ["ContinuousBatchingEngine", "GenerationStream",
           "register_engine", "get_engine", "unregister_engine",
           "SloScheduler", "SloRejected", "ServiceRateEstimator",
           "FeedbackController", "FleetLauncher"]
