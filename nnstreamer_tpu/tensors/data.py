"""Typed scalar/tensor math helpers (reference ``tensor_data.c``).

The reference implements per-dtype get/set/typecast/average in C for use by
tensor_if / tensor_crop / tensor_transform. Here the elementwise work is XLA's
job; these helpers cover the host-side scalar paths (condition evaluation,
crop coordinate extraction) plus saturating typecast semantics matching the
reference's behavior for integer narrowing.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.tensors.types import TensorType


def typecast(arr, dst: TensorType):
    """Cast with C-style saturation for float->int (reference
    ``gst_tensor_data_typecast``, tensor_data.c)."""
    dst = TensorType.from_any(dst)
    dt = dst.np_dtype
    a = np.asarray(arr)
    if np.issubdtype(dt, np.integer) and np.issubdtype(a.dtype, np.floating):
        inf = np.iinfo(dt)
        a = np.clip(a, inf.min, inf.max)
    return a.astype(dt)


def average(arr) -> float:
    """Scalar mean of a tensor (reference ``gst_tensor_data_average``)."""
    return float(np.mean(np.asarray(arr, dtype=np.float64)))


def scalar_at(arr, flat_index: int) -> float:
    """Value at a flat index, as float (reference per-dtype get)."""
    return float(np.asarray(arr).reshape(-1)[flat_index])
