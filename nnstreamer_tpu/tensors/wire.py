"""Reference wire-format contract shared by the serialization codecs
(protobuf / flexbuf / flatbuf).

Single source of truth for the cross-framework constraints every
reference-compatible codec inherits:

- the reference ``tensor_type`` enum order (tensor_typedef.h:154-166):
  ``_NNS_INT32=0 … _NNS_UINT64=9`` then ``_NNS_END`` — 10 values, no
  fp16/bf16;
- the reference ``tensor_format`` order (tensor_typedef.h:201-208):
  static=0 / flexible=1 / sparse=2;
- ``NNS_TENSOR_RANK_LIMIT == 4`` (tensor_typedef.h:34): exactly four
  dimension entries on the wire, 1-padded, innermost-first;
- ``NNS_TENSOR_SIZE_LIMIT == 16`` (tensor_typedef.h:35).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
    TensorType,
)

TYPE_ORDER = list(TensorType)
REF_TYPE_COUNT = 10
FORMAT_ORDER = list(TensorFormat)
REF_RANK = 4
REF_SIZE_LIMIT = 16


def ref_type_index(info: TensorInfo, codec: str, alt: str) -> int:
    """Reference enum value for a tensor's dtype, or a pointed refusal
    when the reference enum has no such value (fp16/bf16)."""
    idx = TYPE_ORDER.index(info.type)
    if idx >= REF_TYPE_COUNT:
        raise ValueError(
            f"{codec} codec: {info.type.value} has no value in the "
            "reference tensor_type enum (tensor_typedef.h:154-166); "
            f"typecast first or use {alt}")
    return idx


def ref_type_from_index(idx: int, codec: str) -> TensorType:
    if not 0 <= idx < REF_TYPE_COUNT:
        raise ValueError(f"{codec} codec: unknown tensor_type value {idx}")
    return TYPE_ORDER[idx]


def ref_dims(info: TensorInfo, codec: str, alt: str) -> List[int]:
    """Wire dimension list: exactly REF_RANK entries, 1-padded,
    innermost-first (the reference's dimension-array convention)."""
    if len(info.dim) > REF_RANK:
        raise ValueError(
            f"{codec} codec: rank {len(info.dim)} exceeds the reference "
            f"wire rank {REF_RANK}; use {alt} for higher-rank tensors")
    return list(info.dim) + [1] * (REF_RANK - len(info.dim))


def ref_format_index(fmt) -> int:
    return FORMAT_ORDER.index(TensorFormat.from_any(fmt))


def ref_format_from_index(idx: int, codec: str) -> TensorFormat:
    if not 0 <= idx < len(FORMAT_ORDER):
        raise ValueError(f"{codec} codec: unknown tensor_format value {idx}")
    return FORMAT_ORDER[idx]


def rate_pair(rate: Optional[Fraction]) -> Tuple[int, int]:
    """(rate_n, rate_d) from our Fraction or fractions.Fraction; the
    reference writes 0/1 when the framerate is unknown."""
    if rate is None:
        return 0, 1
    n = int(getattr(rate, "num", getattr(rate, "numerator", 0)))
    d = int(getattr(rate, "den", getattr(rate, "denominator", 1))) or 1
    return n, d
