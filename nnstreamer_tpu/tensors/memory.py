"""HBM budget accountant + weight/slab residency (the device-memory
resilience layer).

Nothing in the stack previously tracked who owns device memory: a weight
load, a pool window slab, or a growing stream of H2D frame transfers
could exhaust HBM and the first allocation to lose surfaced as an
unhandled ``RESOURCE_EXHAUSTED`` crash somewhere on the hot path. This
module is the substrate the multi-tenant model fabric lands on:

- **Budget accountant** (:class:`HbmBudget`). ``NNSTPU_HBM_BUDGET``
  (bytes; ``k``/``m``/``g`` suffixes) installs a process-wide accountant
  (``ACTIVE``). Every tracked entry point — ``TensorBuffer.to_device`` /
  ``upload_many`` frame transfers, ``BufferPool`` slab growth, backend
  weight loads — registers its bytes against the budget, keeping
  per-category used counters, a high-water mark, and the ``nns_mem_*``
  gauges live. Lint rule NNS113 keeps new ``jax.device_put`` call sites
  inside these tracked entry points.

- **Residency ladder** (:class:`ResidencyManager`). Model weights (and
  any other reloadable device allocation) register as *evictable units*:
  the host pytree is kept as staging, the device copy can be dropped
  under pressure (LRU) and is re-loaded — "prefetch on route" — the next
  time the owning filter touches it. Two models whose weights sum past
  the budget thrash between resident and staged but keep serving
  byte-identical results from one pipeline.

- **Pressure accounting for the degrade ladder.** On budget breach the
  accountant first reclaims cold residency units inline (rung 1 of the
  pressure ladder in ``pipeline/supervise.py``); the remaining overage
  feeds :meth:`HbmBudget.admission_backlog`, the memory-backlog term the
  SLO scheduler adds to its admission estimate so sustained pressure
  sheds at the door instead of OOM-ing mid-pipeline.

Kill switch: with ``NNSTPU_HBM_BUDGET`` unset ``ACTIVE`` stays ``None``
and every hook in pool/buffer/backend code is one module-attribute read
plus an ``is None`` test — byte-identical to a build without this
module, matching the ``NNSTPU_FAULTS`` / ``NNSTPU_TRACE`` discipline.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from nnstreamer_tpu.log import get_logger

log = get_logger("memory")

_ENV = "NNSTPU_HBM_BUDGET"

#: process-wide accountant; ``None`` (the default) means no budget and
#: zero accounting on any hot path. Hot sites read this directly
#: (``memory.ACTIVE``).
ACTIVE: Optional["HbmBudget"] = None

#: the degrade rungs, in escalation order — shared with
#: ``pipeline/supervise.py`` and docs/robustness.md
PRESSURE_RUNGS = ("evict", "pool", "shed", "cpu")


def parse_bytes(text: str) -> int:
    """``"512m"`` → bytes. Accepts a plain integer or a ``k``/``m``/``g``
    (KiB/MiB/GiB) suffix, case-insensitive."""
    s = str(text).strip().lower()
    mult = 1
    for suf, m in (("g", 1 << 30), ("m", 1 << 20), ("k", 1 << 10),
                   ("b", 1)):
        if s.endswith(suf):
            s = s[: -len(suf)].strip()
            mult = m
            break
    try:
        val = float(s)
    except ValueError:
        raise ValueError(f"{_ENV}: cannot parse byte size {text!r}") \
            from None
    if val <= 0:
        raise ValueError(f"{_ENV}: byte size must be positive, got {text!r}")
    return int(val * mult)


def pytree_nbytes(tree: Any) -> int:
    """Host-side byte size of a params pytree (the registration size of
    a residency unit)."""
    import numpy as np

    try:
        import jax

        leaves = jax.tree.leaves(tree)
    except Exception:  # noqa: BLE001 — no jax / not a pytree: best-effort
        leaves = tree if isinstance(tree, (list, tuple)) else [tree]
    total = 0
    for leaf in leaves:
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = np.asarray(leaf).nbytes
        total += int(n)
    return total


#: device-slot sentinel for PINNED units (adopted external placements):
#: "resident" without this manager holding the real arrays
_PINNED = object()


class ResidencyUnit:
    """One evictable device allocation: host staging + a loader that
    re-creates the device copy. The unit is the ONLY holder of the
    device reference — owners fetch it per use via :meth:`value` (which
    touches the LRU and reloads after an eviction), so dropping the
    unit's reference genuinely frees the HBM.

    Two mesh-serving variants:

    - ``group``: per-shard units of ONE sharded/replicated placement.
      The group loads as a whole (one loader call installs the device
      value into every member) and evicts as a whole — a single chip's
      slice of a mesh placement cannot be freed alone, so accounting
      must not pretend it can.
    - ``pinned``: accounting-only adoption of a placement whose arrays
      the OWNER holds (training params, a serving engine). Counted in
      ``nns_mem_used_bytes`` but never an eviction victim — evicting
      would free nothing while the owner's references live.
    - ``on_drop``: a DROPPABLE unit — owner-held bytes (like ``pinned``)
      that the owner can surrender on demand (a prefix-cache entry, a
      regenerable scratch buffer). Eviction calls ``on_drop(key)`` so
      the owner releases its reference, un-registers the bytes, and
      removes the unit — there is no host staging and no reload.

    ``category`` names the budget bucket the unit's bytes count under
    (``weights`` by default; the serving prefix/KV caches use
    ``kvcache``), so ``nns_mem_used_bytes`` splits honestly by owner
    kind instead of lumping every residency unit into weights.
    """

    __slots__ = ("key", "label", "nbytes", "_host", "_loader", "_device",
                 "loads", "evictions", "group", "pinned", "category",
                 "on_drop")

    def __init__(self, key: str, host_value: Any, nbytes: int,
                 loader: Optional[Callable[[Any], Any]], label: str = "",
                 group: Optional[str] = None, pinned: bool = False,
                 category: str = "weights",
                 on_drop: Optional[Callable[[str], None]] = None):
        self.key = key
        self.label = label or key
        self.nbytes = int(nbytes)
        self._host = host_value
        self._loader = loader
        self._device: Any = None
        self.loads = 0
        self.evictions = 0
        self.group = group
        self.pinned = bool(pinned)
        self.category = category
        self.on_drop = on_drop

    @property
    def resident(self) -> bool:
        return self._device is not None

    def value(self) -> Any:
        """The device copy, loading it (back) in if evicted. Delegates to
        the manager so eviction-to-fit and LRU touch stay under one
        lock."""
        mgr = ACTIVE.residency if ACTIVE is not None else None
        if mgr is None:
            # accountant deactivated after registration (tests): serve
            # the host value — callers device_put implicitly downstream
            return self._device if self._device is not None else self._host
        return mgr._ensure(self)


class ResidencyManager:
    """LRU over :class:`ResidencyUnit`\\ s. Eviction drops the device
    reference (the host staging copy persists), un-registers the bytes,
    and counts ``nns_mem_evictions_total``; the next :meth:`value` on the
    unit reclaims space from colder units and reloads — byte-identical
    because the loader round-trips the SAME host values."""

    def __init__(self, budget: "HbmBudget"):
        self._budget = budget
        self._lock = threading.RLock()
        #: key → unit, ordered coldest-first (OrderedDict as LRU)
        self._units: "OrderedDict[str, ResidencyUnit]" = OrderedDict()
        #: group name → member units (mesh per-shard groups)
        self._groups: Dict[str, list] = {}

    # -- registration -------------------------------------------------------
    def register(self, key: str, host_value: Any, nbytes: int,
                 loader: Callable[[Any], Any],
                 label: str = "", group: Optional[str] = None
                 ) -> ResidencyUnit:
        """Adopt a reloadable device allocation. Does NOT load — the
        first :meth:`ResidencyUnit.value` does, under the budget.
        ``group`` names a mesh per-shard group: one loader call loads
        (and one eviction drops) every member together."""
        unit = ResidencyUnit(key, host_value, int(nbytes), loader, label,
                             group=group)
        with self._lock:
            old = self._units.pop(key, None)
            if old is not None:
                self._evict_locked(old)
                self._drop_from_group(old)
            self._units[key] = unit
            if group is not None:
                self._groups.setdefault(group, []).append(unit)
        return unit

    def adopt(self, key: str, nbytes: int, label: str = ""
              ) -> ResidencyUnit:
        """Account an externally-held device placement (mesh-sharded
        training params, serving-engine weights) as a PINNED unit: the
        bytes register now and un-register at :meth:`unregister`; the
        unit is never an eviction victim because this manager does not
        hold the arrays and could free nothing."""
        unit = ResidencyUnit(key, None, int(nbytes), None, label,
                             pinned=True)
        unit._device = _PINNED
        with self._lock:
            old = self._units.pop(key, None)
            if old is not None:
                self._evict_locked(old)
                self._drop_from_group(old)
            self._units[key] = unit
        self._budget.register(unit.nbytes, "weights")
        return unit

    def register_droppable(self, key: str, nbytes: int,
                           on_drop: Callable[[str], None],
                           label: str = "", category: str = "kvcache"
                           ) -> ResidencyUnit:
        """Account owner-held bytes the owner can SURRENDER on demand (a
        prefix-cache entry, a regenerable scratch buffer). Unlike
        :meth:`adopt` the unit IS an eviction victim: under pressure the
        manager calls ``on_drop(key)`` (outside no locks the owner
        needs), un-registers the bytes and forgets the unit — there is
        no host staging and no reload. Registers under ``category`` so
        cache bytes show up as ``kvcache``, not ``weights``."""
        unit = ResidencyUnit(key, None, int(nbytes), None, label,
                             category=category, on_drop=on_drop)
        unit._device = _PINNED      # resident from creation, owner-held
        with self._lock:
            old = self._units.pop(key, None)
            if old is not None:
                self._evict_locked(old)
                self._drop_from_group(old)
            self._units[key] = unit
        self._budget.register(unit.nbytes, category)
        return unit

    def unregister(self, key: str) -> None:
        """Drop a unit (owner closed): its device bytes un-register and
        the host staging reference is released."""
        with self._lock:
            unit = self._units.pop(key, None)
            if unit is None:
                return
            if unit.resident:
                unit._device = None
                self._budget.unregister(unit.nbytes, unit.category)
            unit._host = None
            self._drop_from_group(unit)

    def _drop_from_group(self, unit: ResidencyUnit) -> None:
        if unit.group is None:
            return
        members = self._groups.get(unit.group)
        if members is not None:
            members[:] = [u for u in members if u is not unit]
            if not members:
                self._groups.pop(unit.group, None)

    def _peers_locked(self, unit: ResidencyUnit) -> list:
        if unit.group is None:
            return [unit]
        return list(self._groups.get(unit.group, ())) or [unit]

    # -- residency ----------------------------------------------------------
    def _ensure(self, unit: ResidencyUnit) -> Any:
        with self._lock:
            if unit.resident:
                self._units.move_to_end(unit.key)  # LRU touch
                return unit._device
            # prefetch-on-route: make room among COLDER units, then load.
            # A grouped (per-shard) unit loads its WHOLE group in one
            # loader call — the placement is one sharded/replicated
            # pytree, so partial residency does not exist.
            peers = self._peers_locked(unit)
            needed = sum(p.nbytes for p in peers if not p.resident)
            self.reclaim(needed, keep=unit)
            dev = unit._loader(unit._host)
            unit.loads += 1
            if unit.loads > 1:
                self._budget._m["prefetches"].inc()
                _mark("mem_prefetch", unit=unit.label, nbytes=needed)
            for p in peers:
                if p.resident:
                    continue
                p._device = dev
                if p is not unit:
                    p.loads += 1
                self._units.move_to_end(p.key)
                self._budget.register(p.nbytes, p.category, reclaim=False)
            self._units.move_to_end(unit.key)
            return dev

    def _evict_locked(self, unit: ResidencyUnit) -> int:
        """Drop ``unit`` (and, for a grouped unit, its whole per-shard
        group) to host staging. Returns bytes freed."""
        if not unit.resident or unit.pinned:
            return 0
        if unit.on_drop is not None:
            # Droppable unit: no host staging — surrender the owner's
            # allocation entirely and forget the unit.
            unit._device = None
            unit.evictions += 1
            self._budget.unregister(unit.nbytes, unit.category)
            self._budget._m["evictions"].inc()
            self._units.pop(unit.key, None)
            try:
                unit.on_drop(unit.key)
            except Exception:  # noqa: BLE001 — owner callback, best-effort
                log.warning("on_drop callback for %s raised", unit.label,
                            exc_info=True)
            _mark("mem_evict", unit=unit.label, nbytes=unit.nbytes)
            log.info("dropped cache unit %s (%d bytes)", unit.label,
                     unit.nbytes)
            return unit.nbytes
        freed = 0
        for p in self._peers_locked(unit):
            if not p.resident:
                continue
            p._device = None
            p.evictions += 1
            freed += p.nbytes
            self._budget.unregister(p.nbytes, p.category)
            self._budget._m["evictions"].inc()
        _mark("mem_evict", unit=unit.label, nbytes=freed)
        log.info("evicted residency unit %s (%d bytes) to host staging",
                 unit.label, freed)
        return freed

    def reclaim(self, needed: int, keep: Optional[ResidencyUnit] = None
                ) -> int:
        """Evict coldest-first until ``needed`` bytes fit under the
        budget (or no evictable units remain). Returns bytes freed."""
        freed = 0
        with self._lock:
            keep_group = keep.group if keep is not None else None
            for unit in list(self._units.values()):
                if self._budget.headroom() >= needed:
                    break
                if unit is keep or unit.pinned or not unit.resident:
                    continue
                if keep_group is not None and unit.group == keep_group:
                    continue  # the touched unit's own shard peers
                freed += self._evict_locked(unit)
        return freed

    def evict_all(self) -> int:
        """Pressure-ladder rung 1: drop every resident unit to host
        staging. They reload on their next touch. Pinned units stay —
        their arrays are owner-held and an eviction would free
        nothing."""
        freed = 0
        with self._lock:
            # list(): droppable units delete themselves from _units
            # mid-eviction, which would break a live dict iterator.
            for unit in list(self._units.values()):
                if unit.resident and not unit.pinned:
                    freed += self._evict_locked(unit)
        return freed

    def resident_count(self) -> int:
        with self._lock:
            return sum(1 for u in self._units.values() if u.resident)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            units = [{"key": u.key, "label": u.label, "nbytes": u.nbytes,
                      "resident": u.resident, "loads": u.loads,
                      "evictions": u.evictions, "group": u.group,
                      "pinned": u.pinned, "category": u.category}
                     for u in self._units.values()]
        return {"units": units,
                "resident": sum(1 for u in units if u["resident"])}

    # -- serving continuity --------------------------------------------------
    # (checkpoint_state/restore_state, distinct from the reporting
    # snapshot() above — NNS115 checks the pair's key symmetry)
    def checkpoint_state(self) -> Dict[str, Any]:
        """Durable state for ``Pipeline.checkpoint()``: the LRU order,
        coldest-first, by LABEL. Unit keys embed ``id()``s and are not
        stable across processes; labels (the model identity) are."""
        with self._lock:
            return {"lru": [u.label for u in self._units.values()]}

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Re-impose a saved LRU order onto the units the new process
        registered: each saved label's first matching unit moves to the
        warm end in saved order, so the first pressure event evicts the
        same victims the old process would have. Units with no saved
        label (new models) end up coldest — they have no history to
        claim warmth from."""
        order = state.get("lru") or []
        with self._lock:
            by_label: Dict[str, list] = {}
            for key, u in self._units.items():
                by_label.setdefault(u.label, []).append(key)
            for label in order:
                keys = by_label.get(label)
                if keys:
                    self._units.move_to_end(keys.pop(0))


class HbmBudget:
    """Process-wide device-memory budget: tracked entry points register
    and un-register bytes per category (``weights`` / ``pool`` /
    ``frames``); a register that breaches the limit reclaims cold
    residency units inline and counts a pressure event. The budget is
    advisory accounting, not an allocator — a breach degrades (evict,
    shed) rather than fails the allocation."""

    def __init__(self, limit_bytes: int):
        self.limit = int(limit_bytes)
        if self.limit <= 0:
            raise ValueError("HBM budget must be positive")
        self._lock = threading.RLock()
        self._used: Dict[str, int] = {}
        self.high_water = 0
        self.pressure_events = 0
        #: EWMA of per-frame H2D bytes — converts memory overage into the
        #: synthetic frame backlog the SLO scheduler adds at admission
        self._frame_bytes_ewma = 0.0
        self.residency = ResidencyManager(self)
        self._m = self._make_metrics()

    def _make_metrics(self) -> Dict[str, Any]:
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        ref = weakref.ref(self)
        reg.gauge("nns_mem_budget_bytes",
                  "Configured HBM budget (NNSTPU_HBM_BUDGET)",
                  fn=lambda: (ref().limit if ref() is not None else 0))
        reg.gauge("nns_mem_used_bytes",
                  "Bytes currently registered against the HBM budget "
                  "(weights + pool slabs + in-flight frame transfers)",
                  fn=lambda: (ref().used_bytes() if ref() is not None
                              else 0))
        reg.gauge("nns_mem_high_water_bytes",
                  "High-water mark of registered device bytes",
                  fn=lambda: (ref().high_water if ref() is not None
                              else 0))
        reg.gauge("nns_mem_resident_units",
                  "Residency units currently holding a device copy",
                  fn=lambda: (ref().residency.resident_count()
                              if ref() is not None else 0))
        return {
            "evictions": reg.counter(
                "nns_mem_evictions_total",
                "Residency units evicted to host staging under budget "
                "pressure"),
            "prefetches": reg.counter(
                "nns_mem_prefetches_total",
                "Evicted residency units reloaded to the device on "
                "route"),
            "pressure": {
                rung: reg.counter(
                    "nns_mem_pressure_events_total",
                    "Pressure-ladder rungs taken (budget breach or "
                    "injected OOM)", rung=rung)
                for rung in PRESSURE_RUNGS
            },
        }

    # -- accounting (hot path) ----------------------------------------------
    def register(self, nbytes: int, category: str = "frames",
                 reclaim: bool = True) -> None:
        """Account ``nbytes`` of device memory to ``category``. On breach
        the accountant reclaims cold residency units inline (pressure
        rung 1); any remaining overage is visible to the scheduler via
        :meth:`admission_backlog`."""
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            self._used[category] = self._used.get(category, 0) + n
            used = sum(self._used.values())
            if used > self.high_water:
                self.high_water = used
            breached = used > self.limit
        if breached and reclaim:
            self.pressure_events += 1
            self.count_pressure("evict")
            _mark("mem_pressure", used=used, limit=self.limit,
                  category=category)
            self.residency.reclaim(0)

    def unregister(self, nbytes: int, category: str = "frames") -> None:
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            cur = self._used.get(category, 0) - n
            if cur <= 0:
                self._used.pop(category, None)
                if cur < 0:
                    log.warning("HBM budget underflow in category %r "
                                "(%d bytes over-released)", category, -cur)
            else:
                self._used[category] = cur

    def note_h2d(self, nbytes: int, owner: Any = None) -> None:
        """Register an H2D frame transfer. ``owner`` (the Python wrapper
        holding the device arrays — a (Device)Buffer, not a jax array)
        un-registers the bytes when it dies, so frame bytes track the
        live working set, not cumulative traffic."""
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            a = 0.2
            self._frame_bytes_ewma = (
                n if self._frame_bytes_ewma == 0.0
                else (1 - a) * self._frame_bytes_ewma + a * n)
        self.register(n, "frames")
        if owner is not None:
            try:
                weakref.finalize(owner, _finalize_frames, weakref.ref(self),
                                 n)
            except TypeError:
                # not weakref-able: count the transfer but let the bytes
                # expire immediately rather than leak forever
                self.unregister(n, "frames")

    # -- state --------------------------------------------------------------
    def used_bytes(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def headroom(self) -> int:
        return self.limit - self.used_bytes()

    def overage(self) -> int:
        return max(0, -self.headroom())

    def breached(self) -> bool:
        return self.used_bytes() > self.limit

    def admission_backlog(self) -> int:
        """The memory-backlog term for ``SloScheduler.decide``: current
        overage expressed in frames (via the per-frame H2D byte EWMA), so
        sustained pressure inflates the admission estimate and new work
        sheds at the door. Pure state read — no waits, no clock
        (NNS110-safe)."""
        over = self.overage()
        if over <= 0:
            return 0
        with self._lock:
            per_frame = self._frame_bytes_ewma
        if per_frame <= 0:
            return 1
        return max(1, int(over / per_frame))

    def count_pressure(self, rung: str) -> None:
        c = self._m["pressure"].get(rung)
        if c is not None:
            c.inc()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            used = dict(self._used)
        res = self.residency.snapshot()
        return {
            "budget_bytes": self.limit,
            "used_bytes": sum(used.values()),
            "used_by_category": used,
            "high_water_bytes": self.high_water,
            "headroom_bytes": self.limit - sum(used.values()),
            "evictions": int(self._m["evictions"].value),
            "prefetches": int(self._m["prefetches"].value),
            "pressure_events": self.pressure_events,
            "resident_units": res["resident"],
            "units": res["units"],
        }


def _finalize_frames(budget_ref, nbytes: int) -> None:
    """Module-level finalizer target: un-register a dead frame wrapper's
    H2D bytes against the SAME accountant that registered them (a
    re-activated accountant must not absorb stale releases)."""
    budget = budget_ref()
    if budget is not None:
        budget.unregister(nbytes, "frames")


def _mark(kind: str, **args) -> None:
    from nnstreamer_tpu.obs import timeline as _timeline

    tl = _timeline.ACTIVE
    if tl is not None:
        tl.mark(kind, None, track="memory", **args)


# --------------------------------------------------------------------------
# activation (the NNSTPU_FAULTS/NNSTPU_TRACE kill-switch discipline)
# --------------------------------------------------------------------------
def activate(limit_bytes: int) -> HbmBudget:
    """Install a fresh process-wide accountant and return it."""
    global ACTIVE
    ACTIVE = HbmBudget(int(limit_bytes))
    log.info("HBM budget active: %d bytes", ACTIVE.limit)
    return ACTIVE


def deactivate() -> None:
    global ACTIVE
    ACTIVE = None


def maybe_activate_env() -> Optional[HbmBudget]:
    """``Pipeline.start()`` hook: honor ``NNSTPU_HBM_BUDGET`` without
    code changes. Idempotent; an explicitly installed accountant wins."""
    if ACTIVE is not None:
        return ACTIVE
    spec = os.environ.get(_ENV, "").strip()
    if not spec:
        return None
    return activate(parse_bytes(spec))
