"""Ingest buffer pool — recycled, aligned host staging buffers.

The reference ships a ``tensor_allocator`` so per-frame payloads come out
of a reused allocation instead of malloc/free per buffer; GStreamer itself
pools via ``GstBufferPool``. Our ingest hot path had neither: every source
frame, converter stack, and aggregator window concatenation allocated a
fresh numpy array, and at flagship rates (batch=8 × 224×224×3 uint8) that
host allocation traffic is a real slice of the 486-fps ingest bound the
bench measures. This module is the tensor_allocator analog:

- **Size-classed free lists.** Requests round up to a power-of-two byte
  class; a released slab serves any same-class request regardless of
  shape/dtype (the view is re-derived per acquire).
- **Aligned.** Slabs are offset to ``align`` (default 64) byte boundaries
  so XLA's host ingestion path (and any zero-copy H2D that requires
  alignment) never falls off its fast path.
- **Safe recycling.** ``acquire`` registers a GC finalizer on the view it
  hands out: a buffer that flows to the end of a pipeline and is simply
  dropped returns its slab to the free list the moment the last reference
  dies — no explicit release required for correctness. ``release`` is the
  explicit fast path for owners that KNOW the array is dead (e.g. the
  dispatch window fencing the batch that consumed a staging buffer); it
  detaches the finalizer so a recycled id can never double-free. Both
  paths refcount-check the slab before recycling: numpy collapses view
  chains (``frame[None].base`` is the slab, not our view), so a live
  derived view downstream means the slab is dropped to plain GC rather
  than handed to the next acquire.

Instrumented with ``nns_pool_hits_total`` / ``nns_pool_misses_total`` /
``nns_pool_grows_total`` counters and ``nns_pool_outstanding`` /
``nns_pool_bytes_held`` gauges in ``obs/``. Disable with ``NNSTPU_POOL=0``
(acquire degrades to plain ``np.empty``).

**Window slabs.** The transfer-batching layer (``tensors/buffer.py``
``upload_many``) stages one dispatch window's frames in ONE contiguous
slab — ``acquire_window`` carves per-frame slot views out of a single
pool allocation so the whole window crosses H2D as one ``device_put``.
``contiguous_window_view`` is the zero-copy fast path: frames that were
already written into consecutive slots of one slab (ingest-lane window
staging, ``pipeline/lanes.py``) are re-wrapped as the stacked upload view
with no host copy at all.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: smallest size class in bytes — tiny requests all share one class
_MIN_CLASS = 256


def pool_enabled() -> bool:
    return os.environ.get("NNSTPU_POOL", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


def _size_class(nbytes: int) -> int:
    if nbytes <= _MIN_CLASS:
        return _MIN_CLASS
    return 1 << (nbytes - 1).bit_length()


def _alloc_fault_check(nbytes: int) -> None:
    """``pool.alloc`` chaos hook on the slab-growth (miss) path only —
    the recycled-slab hot path never pays. Resolved via sys.modules so
    the tensors layer never imports the pipeline layer: an injector can
    only exist once its module is imported."""
    import sys

    faults = sys.modules.get("nnstreamer_tpu.pipeline.faults")
    if faults is None:
        return
    fi = faults.ACTIVE
    if fi is not None:
        fi.check("pool.alloc")


def _mem_account(nbytes: int, grow: bool) -> None:
    """Register/un-register slab bytes with the HBM budget accountant
    (``tensors/memory.py``). Pool slabs are host staging, but they are
    pinned transfer sources whose lifetime bounds device windows — the
    accountant tracks them as the ``pool`` category so the pressure
    ladder's release-pools rung has a number to reclaim. No accountant
    (the default) means one dict lookup and out."""
    import sys

    mem = sys.modules.get("nnstreamer_tpu.tensors.memory")
    if mem is None:
        return
    acct = mem.ACTIVE
    if acct is None:
        return
    if grow:
        acct.register(nbytes, "pool")
    else:
        acct.unregister(nbytes, "pool")


class BufferPool:
    """Thread-safe, size-classed pool of aligned host staging buffers."""

    def __init__(self, align: int = 64, max_per_class: int = 32,
                 name: str = "ingest"):
        self.align = int(align)
        self.max_per_class = int(max_per_class)
        self.name = name
        self._lock = threading.Lock()
        #: size class → list of free slabs (uint8 arrays, len = class+align)
        self._free: Dict[int, List[np.ndarray]] = {}
        #: id(view) → (class, slab, finalizer) for live pool-owned views
        self._out: Dict[int, Tuple[int, np.ndarray, Any]] = {}
        #: id(view) → pin count: views adopted as a DeviceBuffer's cached
        #: host view; explicit release is refused while pinned (the
        #: refcount guard alone cannot see the cache — the cache keeps the
        #: *view* alive, so the view's own `.base` still accounts for the
        #: slab ref the guard expects from a dying array)
        self._pinned: Dict[int, int] = {}
        self.hits = 0
        self.misses = 0
        self.grows = 0
        self._metrics = None

    # -- obs ----------------------------------------------------------------
    def _obs(self):
        if self._metrics is None:
            from nnstreamer_tpu.obs import get_registry

            reg = get_registry()
            labels = {"pool": self.name}
            ref = weakref.ref(self)
            self._metrics = {
                "hits": reg.counter(
                    "nns_pool_hits_total",
                    "Acquires served from a recycled slab", **labels),
                "misses": reg.counter(
                    "nns_pool_misses_total",
                    "Acquires that found no free slab in the class",
                    **labels),
                "grows": reg.counter(
                    "nns_pool_grows_total",
                    "Fresh slab allocations (pool footprint growth)",
                    **labels),
            }
            reg.gauge(
                "nns_pool_outstanding",
                "Pool-owned buffers currently held by the pipeline",
                fn=lambda: (len(ref()._out) if ref() is not None else 0),
                **labels)
            reg.gauge(
                "nns_pool_bytes_held",
                "Bytes the pool currently holds (free slabs + slabs "
                "backing outstanding views) — the footprint number "
                "previously only inferable from the miss/grow counters",
                fn=lambda: (ref().bytes_held() if ref() is not None else 0),
                **labels)
        return self._metrics

    # -- hot path -----------------------------------------------------------
    def acquire(self, shape, dtype) -> np.ndarray:
        """An uninitialized, ``align``-byte-aligned array of (shape, dtype)
        backed by a recycled slab when one is free."""
        shape = tuple(int(s) for s in shape)
        dt = np.dtype(dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if not pool_enabled() or nbytes == 0:
            return np.empty(shape, dt)
        cls = _size_class(nbytes)
        obs = self._obs()
        with self._lock:
            free = self._free.get(cls)
            slab = free.pop() if free else None
        if slab is None:
            self.misses += 1
            self.grows += 1
            obs["misses"].inc()
            obs["grows"].inc()
            _alloc_fault_check(cls + self.align)
            slab = np.empty(cls + self.align, np.uint8)
            _mem_account(cls + self.align, grow=True)
        else:
            self.hits += 1
            obs["hits"].inc()
        off = (-slab.ctypes.data) % self.align
        view = slab[off:off + nbytes].view(dt).reshape(shape)
        token = id(view)
        fin = weakref.finalize(view, self._expire, token)
        with self._lock:
            self._out[token] = (cls, slab, fin)
        return view

    def _expire(self, token: int) -> None:
        """GC fallback: the view died without an explicit release.

        The slab is recycled ONLY when nothing else references it. numpy
        collapses view chains — a derived view's ``.base`` is the slab,
        not the view we handed out — so the tracked view can die while a
        downstream ``frame[None]``/slice still reads the slab. Each such
        base reference shows up in the slab's refcount; if any remain,
        the slab is dropped (plain GC frees it when the last view dies)
        instead of re-entering the free list."""
        import sys

        with self._lock:
            # the view is dead, so any pin on it is moot (wrapper and view
            # can die in the same GC pass, finalizer order undefined)
            self._pinned.pop(token, None)
            entry = self._out.pop(token, None)
            if entry is None:
                return
            cls, slab = entry[0], entry[1]
            del entry
            # refs now: local `slab` + getrefcount's argument + the DYING
            # view's .base (tp_dealloc fires weakref callbacks before it
            # drops the instance's own references) == 3
            if sys.getrefcount(slab) > 3:
                # a derived view is still live — never alias it
                _mem_account(cls + self.align, grow=False)
                return
            free = self._free.setdefault(cls, [])
            if len(free) < self.max_per_class:
                free.append(slab)
            else:
                _mem_account(cls + self.align, grow=False)

    def owns(self, arr) -> bool:
        """True if ``arr`` is a view this pool handed out (not a derived
        view — those pin the slab out of circulation until they die)."""
        with self._lock:
            return id(arr) in self._out

    def pin(self, arr) -> bool:
        """Pin a pool-owned view against explicit release: a DeviceBuffer
        adopted it as its lazy host-view cache, so the usual "the staging
        array is dead after the fence" contract no longer holds — the
        sink/dispatch release sites must NOT hand its slab to the next
        acquire while the cache can still be read. A pinned view's slab
        only recycles through the GC fallback once the view truly dies.
        Returns False (no-op) for arrays this pool does not own."""
        with self._lock:
            token = id(arr)
            if token not in self._out:
                return False
            self._pinned[token] = self._pinned.get(token, 0) + 1
            return True

    def unpin(self, token: int) -> None:
        """Drop one pin (the adopting wrapper died). ``token`` is the
        ``id()`` of the pinned view — the wrapper's finalizer cannot hold
        the array itself."""
        with self._lock:
            n = self._pinned.get(token, 0)
            if n <= 1:
                self._pinned.pop(token, None)
            else:
                self._pinned[token] = n - 1

    def release(self, arr) -> bool:
        """Explicitly return ``arr``'s slab to the free list. Only call
        when no other reader (host or in-flight device transfer) can
        still touch the memory. Unknown arrays are ignored (False).
        Pinned arrays (a DeviceBuffer host-view cache reads them) are
        refused — their slab recycles via GC when the view dies."""
        import sys

        with self._lock:
            if id(arr) in self._pinned:
                return False
            entry = self._out.pop(id(arr), None)
            if entry is None:
                return False
            cls, slab, fin = entry
            del entry
            fin.detach()  # a future acquire may reuse this id — the stale
            # finalizer must never fire against the new registration
            # refs now: local `slab` + getrefcount arg + `arr.base` == 3;
            # more means a derived view (numpy collapses .base to the
            # slab) is still live somewhere — drop the slab instead of
            # recycling it under that reader
            if sys.getrefcount(slab) > 3:
                _mem_account(cls + self.align, grow=False)
                return True
            free = self._free.setdefault(cls, [])
            if len(free) < self.max_per_class:
                free.append(slab)
            else:
                _mem_account(cls + self.align, grow=False)
            return True

    def release_many(self, arrs) -> int:
        return sum(1 for a in (arrs or ()) if self.release(a))

    # -- window staging -----------------------------------------------------
    def acquire_window(self, frames: int, shape, dtype) -> np.ndarray:
        """One contiguous ``(frames,) + shape`` staging view backed by a
        SINGLE pool slab: the host side of a batched multi-frame H2D
        upload (``tensors/buffer.py`` ``upload_many``). Slot ``i`` is
        plain ``view[i]`` — numpy collapses the slot's ``.base`` to the
        underlying slab, so the refcount guard in :meth:`release` keeps
        the slab out of circulation while any slot view is still read
        (a DeviceBuffer host view, a late finalize)."""
        return self.acquire((int(frames),) + tuple(shape), dtype)

    def bytes_held(self) -> int:
        """Current pool footprint in bytes: free slabs plus the slabs
        backing outstanding views (each slab is its size class + the
        alignment slack it was allocated with)."""
        with self._lock:
            free_b = sum(cls * len(v) + self.align * len(v)
                         for cls, v in self._free.items())
            out_b = sum(cls + self.align for cls, _s, _f in
                        self._out.values())
        return int(free_b + out_b)

    # -- introspection ------------------------------------------------------
    def hit_rate(self) -> Optional[float]:
        total = self.hits + self.misses
        return (self.hits / total) if total else None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            free = sum(len(v) for v in self._free.values())
            out = len(self._out)
            pinned = len(self._pinned)
        rate = self.hit_rate()
        return {"hits": self.hits, "misses": self.misses,
                "grows": self.grows, "outstanding": out, "free": free,
                "pinned": pinned,
                "hit_rate": None if rate is None else round(rate, 4)}

    def clear(self) -> None:
        """Free whole size-classes: drop every free slab so the pool's
        held footprint returns to its outstanding working set
        (outstanding views are untouched — their slabs recycle or drop
        through the usual release/GC paths). ``Pipeline.stop()`` calls
        this so a stopped pipeline's staging arenas don't pin peak-rate
        slab bytes for the life of the process."""
        with self._lock:
            dropped = sum((cls + self.align) * len(v)
                          for cls, v in self._free.items())
            self._free.clear()
        if dropped:
            _mem_account(dropped, grow=False)


def release_all_pools() -> None:
    """Free the free-lists of every process-wide pool arena (the shared
    ingest pool plus each per-lane arena) — the ``Pipeline.stop()``
    footprint hook behind the ``nns_pool_bytes_held`` gauge."""
    if _default is not None:
        _default.clear()
    with _lane_pools_lock:
        pools = list(_lane_pools.values())
    for p in pools:
        p.clear()


def contiguous_window_view(arrays) -> Optional[np.ndarray]:
    """Zero-copy host side of a batched upload: if ``arrays`` are
    equally-shaped C-contiguous views laid out back-to-back in ONE pool
    slab (consecutive window-slab slots written by the ingest lanes or a
    prior :meth:`BufferPool.acquire_window`), return the single
    ``(k,) + shape`` view spanning them; else None (the caller copies
    into a fresh window slab). The returned view's ``.base`` is the slab
    itself, so it participates in the pool's refcount guard like any
    derived view."""
    k = len(arrays)
    if k < 2:
        return None
    first = arrays[0]
    base = getattr(first, "base", None)
    if base is None or not isinstance(first, np.ndarray):
        return None
    # fast path only for the pool's own slab layout: 1-D uint8 backing
    if not (isinstance(base, np.ndarray) and base.ndim == 1
            and base.dtype == np.uint8 and base.flags["C_CONTIGUOUS"]):
        return None
    shape, dtype, step = first.shape, first.dtype, first.nbytes
    if step == 0 or not first.flags["C_CONTIGUOUS"]:
        return None
    addr0 = first.ctypes.data
    for i, a in enumerate(arrays):
        if (not isinstance(a, np.ndarray) or a.base is not base
                or a.shape != shape or a.dtype != dtype
                or not a.flags["C_CONTIGUOUS"]
                or a.ctypes.data != addr0 + i * step):
            return None
    off = addr0 - base.ctypes.data
    if off < 0 or off + k * step > base.nbytes:
        return None
    return base[off:off + k * step].view(dtype).reshape((k,) + shape)


_default: Optional[BufferPool] = None
_default_lock = threading.Lock()


def get_pool() -> BufferPool:
    """Process-wide ingest pool (sources/converters/aggregators share
    it so a pipeline's steady-state working set converges on a few
    slabs)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = BufferPool()
    return _default


_lane_pools: Dict[int, BufferPool] = {}
_lane_pools_lock = threading.Lock()


def get_lane_pool(lane: int) -> BufferPool:
    """Per-lane staging arena for the ingest lane executor
    (``pipeline/lanes.py``): each worker lane copies its frames into its
    own pool so N lanes never serialize on one free-list lock or trip
    each other's slab refcount guards. Keyed process-wide by lane index
    (lane k of every pipeline shares arena k) so metric label
    cardinality stays bounded by the lane count, not pipeline count."""
    pool = _lane_pools.get(lane)
    if pool is None:
        with _lane_pools_lock:
            pool = _lane_pools.get(lane)
            if pool is None:
                pool = BufferPool(name=f"ingest-lane{lane}")
                _lane_pools[lane] = pool
    return pool
