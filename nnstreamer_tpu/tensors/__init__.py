"""L1 — tensor type system, caps, buffers, and per-memory metadata."""

from nnstreamer_tpu.tensors.types import (  # noqa: F401
    TensorType,
    TensorFormat,
    TensorInfo,
    TensorsInfo,
    TensorsConfig,
    NNS_TENSOR_RANK_LIMIT,
    NNS_TENSOR_SIZE_LIMIT,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer  # noqa: F401
from nnstreamer_tpu.tensors.meta import TensorMetaInfo  # noqa: F401
from nnstreamer_tpu.tensors.pool import (  # noqa: F401
    BufferPool,
    get_pool,
    pool_enabled,
)
