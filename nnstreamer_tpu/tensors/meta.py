"""Serializable per-tensor header for flexible/sparse streams and the wire.

The reference prepends a fixed binary header (``GstTensorMetaInfo``,
``gst/nnstreamer/tensor_meta.c`` / ``tensor_typedef.h:272-297``) to every
memory of a flexible or sparse tensor so each buffer is self-describing:
version magic, dtype, dim[rank], format, and for sparse tensors the
number of non-zero elements. We keep the same idea with an explicit
little-endian layout (struct-packed), used by:

- flexible-format streams (``TensorFormat.FLEXIBLE``) where shapes vary
  per buffer and caps carry no dimensions;
- sparse encode/decode (``elements.sparse``);
- the distributed query protocol's tensor framing (``query.protocol``).

Two selectable wire layouts:

**native** ("TMI1", little-endian, 96 bytes) — the framework's own
framing, used by the query protocol and mode=nnstpu-flex; supports
rank>4 and fp16/bf16::

  u32 magic      0x544D4931 ("TMI1")
  u32 type       TensorType index
  u32 format     TensorFormat index (static=0/flexible=1/sparse=2)
  u32 rank
  u64 dim[8]     innermost-first, unused trailing dims = 1
  u64 media_type reserved (0)
  u64 sparse_nnz nonzero count for sparse payloads, else 0

**reference** — the byte-exact ``GstTensorMetaInfo`` v1 header
(tensor_typedef.h:283-297, packed/parsed by tensor_common.c:1669-1723):
128 bytes of little-endian u32s, interoperable with reference
flexible/sparse pipelines::

  u32 version    0xDE001000  (GST_TENSOR_META_MAKE_VERSION(1,0))
  u32 type       reference tensor_type enum (no fp16/bf16)
  u32 dim[16]    innermost-first, rank-terminated by 0
  u32 format     static=0 / flexible=1 / sparse=2
  u32 media_type _NNS_TENSOR = 4
  u32 nnz        sparse non-zero count (union member; 0 otherwise)
  ...zero-padded to 128 bytes (gst_tensor_meta_info_get_header_size)

``parse_header`` sniffs which layout a buffer carries (the reference
version word always has the 0xDE magic in its top byte; TMI1's magic
differs), so decode paths accept both.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

from nnstreamer_tpu.tensors.types import (
    NNS_TENSOR_RANK_LIMIT,
    TensorFormat,
    TensorInfo,
    TensorType,
)

_MAGIC = 0x544D4931
_TYPE_ORDER = list(TensorType)
_FORMAT_ORDER = list(TensorFormat)
_STRUCT = struct.Struct("<IIII8QQQ")

HEADER_SIZE = _STRUCT.size

#: reference GstTensorMetaInfo v1 constants (tensor_common.c:1510-1525)
REF_META_VERSION = 0xDE001000  # GST_TENSOR_META_MAKE_VERSION(1, 0)
REF_META_VERSION_MASK = 0xDE000000
REF_META_RANK_LIMIT = 16  # NNS_TENSOR_META_RANK_LIMIT (tensor_typedef.h:44)
REF_HEADER_SIZE = 128  # gst_tensor_meta_info_get_header_size, v1
_REF_MEDIA_TENSOR = 4  # _NNS_TENSOR (tensor_typedef.h:185)
_REF_STRUCT = struct.Struct("<21I")  # version,type,dim[16],format,media,nnz


@dataclasses.dataclass
class TensorMetaInfo:
    """Self-describing tensor header (reference ``GstTensorMetaInfo``)."""

    type: TensorType
    dim: Tuple[int, ...]
    format: TensorFormat = TensorFormat.STATIC
    sparse_nnz: int = 0

    def __post_init__(self):
        self.type = TensorType.from_any(self.type)
        self.format = TensorFormat.from_any(self.format)
        self.dim = tuple(int(d) for d in self.dim)

    @classmethod
    def from_info(cls, info: TensorInfo, format=TensorFormat.FLEXIBLE,
                  sparse_nnz: int = 0) -> "TensorMetaInfo":
        return cls(type=info.type, dim=tuple(info.dim), format=format,
                   sparse_nnz=sparse_nnz)

    def to_info(self) -> TensorInfo:
        return TensorInfo(dim=self.dim, type=self.type)

    # -- wire format ---------------------------------------------------------
    def pack(self) -> bytes:
        dim = list(self.dim[:NNS_TENSOR_RANK_LIMIT])
        dim += [1] * (NNS_TENSOR_RANK_LIMIT - len(dim))
        return _STRUCT.pack(
            _MAGIC,
            _TYPE_ORDER.index(self.type),
            _FORMAT_ORDER.index(self.format),
            len(self.dim),
            *dim,
            0,
            self.sparse_nnz,
        )

    @classmethod
    def unpack(cls, data: bytes) -> "TensorMetaInfo":
        if len(data) < HEADER_SIZE:
            raise ValueError(f"header too short: {len(data)} < {HEADER_SIZE}")
        fields = _STRUCT.unpack_from(data)
        magic, type_i, fmt_i, rank = fields[0], fields[1], fields[2], fields[3]
        if magic != _MAGIC:
            raise ValueError(f"bad tensor header magic: {magic:#x}")
        if rank < 1 or rank > NNS_TENSOR_RANK_LIMIT:
            raise ValueError(f"bad rank {rank}")
        if type_i >= len(_TYPE_ORDER):
            raise ValueError(f"bad tensor type index {type_i}")
        if fmt_i >= len(_FORMAT_ORDER):
            raise ValueError(f"bad tensor format index {fmt_i}")
        dim = tuple(int(d) for d in fields[4:4 + rank])
        return cls(
            type=_TYPE_ORDER[type_i],
            dim=dim,
            format=_FORMAT_ORDER[fmt_i],
            sparse_nnz=int(fields[13]),
        )

    # -- reference GstTensorMetaInfo wire format ----------------------------
    def pack_ref(self) -> bytes:
        """Byte-exact ``GstTensorMetaInfo`` v1 header (128 B) the way
        gst_tensor_meta_info_update_header (tensor_common.c:1669-1684)
        memcpys the struct: version, type, dim[16] rank-terminated by
        zero, format, media_type, nnz, zero-padded."""
        from nnstreamer_tpu.tensors import wire

        type_idx = wire.ref_type_index(self.to_info(), "meta",
                                       "the native TMI1 layout")
        if len(self.dim) > REF_META_RANK_LIMIT:
            raise ValueError(f"meta: rank {len(self.dim)} exceeds the "
                             f"reference limit {REF_META_RANK_LIMIT}")
        if any(d <= 0 for d in self.dim):
            raise ValueError(f"meta: invalid dimension {self.dim}")
        dims = list(self.dim) + [0] * (REF_META_RANK_LIMIT - len(self.dim))
        hdr = _REF_STRUCT.pack(
            REF_META_VERSION,
            type_idx,
            *dims,
            wire.ref_format_index(self.format),
            _REF_MEDIA_TENSOR,
            self.sparse_nnz,
        )
        return hdr + b"\x00" * (REF_HEADER_SIZE - len(hdr))

    @classmethod
    def unpack_ref(cls, data: bytes) -> "TensorMetaInfo":
        """Parse a reference v1 header the way
        gst_tensor_meta_info_parse_header (tensor_common.c:1691-1723)
        does, with its validate() checks."""
        from nnstreamer_tpu.tensors import wire

        if len(data) < REF_HEADER_SIZE:
            raise ValueError(
                f"header too short: {len(data)} < {REF_HEADER_SIZE}")
        fields = _REF_STRUCT.unpack_from(data)
        version = fields[0]
        if (version & REF_META_VERSION_MASK) != REF_META_VERSION_MASK:
            raise ValueError(f"bad GstTensorMetaInfo version {version:#x}")
        ttype = wire.ref_type_from_index(fields[1], "meta")
        dims = []
        for d in fields[2:2 + REF_META_RANK_LIMIT]:
            if d == 0:
                break
            dims.append(int(d))
        if not dims:
            raise ValueError("GstTensorMetaInfo header with empty dimension")
        if len(dims) > NNS_TENSOR_RANK_LIMIT:
            raise ValueError(
                f"GstTensorMetaInfo header with rank {len(dims)}: the "
                f"reference wire allows up to {REF_META_RANK_LIMIT} but "
                f"this framework handles rank ≤ {NNS_TENSOR_RANK_LIMIT}")
        fmt = wire.ref_format_from_index(fields[18], "meta")
        if fields[19] > _REF_MEDIA_TENSOR:
            raise ValueError(f"bad media_type {fields[19]}")
        nnz = fields[20] if fmt is TensorFormat.SPARSE else 0
        return cls(type=ttype, dim=tuple(dims), format=fmt, sparse_nnz=nnz)

    @property
    def data_size(self) -> int:
        """Byte size of the dense payload this header describes."""
        return self.to_info().size


def is_ref_header(data: bytes, offset: int = 0) -> bool:
    """True when ``data[offset:]`` starts with a reference
    ``GstTensorMetaInfo`` header (0xDE version magic in the first word;
    the native TMI1 magic never matches it)."""
    if len(data) < offset + 4:
        return False
    (word,) = struct.unpack_from("<I", data, offset)
    return (word & REF_META_VERSION_MASK) == REF_META_VERSION_MASK


def parse_header(data: bytes, offset: int = 0):
    """Sniff the header layout at ``offset``; returns
    ``(TensorMetaInfo, header_size)``."""
    if is_ref_header(data, offset):
        return (TensorMetaInfo.unpack_ref(
            data[offset:offset + REF_HEADER_SIZE]), REF_HEADER_SIZE)
    return (TensorMetaInfo.unpack(data[offset:offset + HEADER_SIZE]),
            HEADER_SIZE)


def pack_tensor(arr, format=TensorFormat.FLEXIBLE,
                layout: str = "native") -> bytes:
    """Serialize one tensor as header + raw bytes (host-side).
    ``layout="reference"`` emits the ``GstTensorMetaInfo`` byte layout a
    reference flexible-stream peer parses; ``"native"`` the TMI1 one."""
    import numpy as np

    if layout not in ("reference", "native"):
        raise ValueError(f"pack_tensor: unknown layout {layout!r} "
                         "(reference|native)")
    arr = np.ascontiguousarray(np.asarray(arr))
    info = TensorInfo.from_array(arr)
    meta = TensorMetaInfo.from_info(info, format=format)
    hdr = meta.pack_ref() if layout == "reference" else meta.pack()
    return hdr + arr.tobytes()


def unpack_tensor(data: bytes, offset: int = 0):
    """Parse header + payload at ``offset``; returns (array, next_offset).
    Accepts both the native and the reference header layouts."""
    import numpy as np

    meta, hsize = parse_header(data, offset)
    start = offset + hsize
    end = start + meta.data_size
    if len(data) < end:
        raise ValueError("truncated tensor payload")
    arr = np.frombuffer(data[start:end], dtype=meta.type.np_dtype).reshape(
        meta.to_info().shape
    )
    return arr, end
