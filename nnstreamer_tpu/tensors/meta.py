"""Serializable per-tensor header for flexible/sparse streams and the wire.

The reference prepends a fixed binary header (``GstTensorMetaInfo``,
``gst/nnstreamer/tensor_meta.c`` / ``tensor_typedef.h:272-297``) to every
memory of a flexible or sparse tensor so each buffer is self-describing:
version magic, dtype, dim[rank], format, and for sparse tensors the
number of non-zero elements. We keep the same idea with an explicit
little-endian layout (struct-packed), used by:

- flexible-format streams (``TensorFormat.FLEXIBLE``) where shapes vary
  per buffer and caps carry no dimensions;
- sparse encode/decode (``elements.sparse``);
- the distributed query protocol's tensor framing (``query.protocol``).

Header layout (little-endian, 96 bytes):
  u32 magic      0x544D4931 ("TMI1")
  u32 type       TensorType index
  u32 format     TensorFormat index (static=0/flexible=1/sparse=2)
  u32 rank
  u64 dim[8]     innermost-first, unused trailing dims = 1
  u64 media_type reserved (0)
  u64 sparse_nnz nonzero count for sparse payloads, else 0
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

from nnstreamer_tpu.tensors.types import (
    NNS_TENSOR_RANK_LIMIT,
    TensorFormat,
    TensorInfo,
    TensorType,
)

_MAGIC = 0x544D4931
_TYPE_ORDER = list(TensorType)
_FORMAT_ORDER = list(TensorFormat)
_STRUCT = struct.Struct("<IIII8QQQ")

HEADER_SIZE = _STRUCT.size


@dataclasses.dataclass
class TensorMetaInfo:
    """Self-describing tensor header (reference ``GstTensorMetaInfo``)."""

    type: TensorType
    dim: Tuple[int, ...]
    format: TensorFormat = TensorFormat.STATIC
    sparse_nnz: int = 0

    @classmethod
    def from_info(cls, info: TensorInfo, format=TensorFormat.FLEXIBLE,
                  sparse_nnz: int = 0) -> "TensorMetaInfo":
        return cls(type=info.type, dim=tuple(info.dim), format=format,
                   sparse_nnz=sparse_nnz)

    def to_info(self) -> TensorInfo:
        return TensorInfo(dim=self.dim, type=self.type)

    # -- wire format ---------------------------------------------------------
    def pack(self) -> bytes:
        dim = list(self.dim[:NNS_TENSOR_RANK_LIMIT])
        dim += [1] * (NNS_TENSOR_RANK_LIMIT - len(dim))
        return _STRUCT.pack(
            _MAGIC,
            _TYPE_ORDER.index(self.type),
            _FORMAT_ORDER.index(self.format),
            len(self.dim),
            *dim,
            0,
            self.sparse_nnz,
        )

    @classmethod
    def unpack(cls, data: bytes) -> "TensorMetaInfo":
        if len(data) < HEADER_SIZE:
            raise ValueError(f"header too short: {len(data)} < {HEADER_SIZE}")
        fields = _STRUCT.unpack_from(data)
        magic, type_i, fmt_i, rank = fields[0], fields[1], fields[2], fields[3]
        if magic != _MAGIC:
            raise ValueError(f"bad tensor header magic: {magic:#x}")
        if rank < 1 or rank > NNS_TENSOR_RANK_LIMIT:
            raise ValueError(f"bad rank {rank}")
        if type_i >= len(_TYPE_ORDER):
            raise ValueError(f"bad tensor type index {type_i}")
        if fmt_i >= len(_FORMAT_ORDER):
            raise ValueError(f"bad tensor format index {fmt_i}")
        dim = tuple(int(d) for d in fields[4:4 + rank])
        return cls(
            type=_TYPE_ORDER[type_i],
            dim=dim,
            format=_FORMAT_ORDER[fmt_i],
            sparse_nnz=int(fields[13]),
        )

    @property
    def data_size(self) -> int:
        """Byte size of the dense payload this header describes."""
        return self.to_info().size


def pack_tensor(arr, format=TensorFormat.FLEXIBLE) -> bytes:
    """Serialize one tensor as header + raw bytes (host-side)."""
    import numpy as np

    arr = np.ascontiguousarray(np.asarray(arr))
    info = TensorInfo.from_array(arr)
    return TensorMetaInfo.from_info(info, format=format).pack() + arr.tobytes()


def unpack_tensor(data: bytes, offset: int = 0):
    """Parse header + payload at ``offset``; returns (array, next_offset)."""
    import numpy as np

    meta = TensorMetaInfo.unpack(data[offset:offset + HEADER_SIZE])
    start = offset + HEADER_SIZE
    end = start + meta.data_size
    if len(data) < end:
        raise ValueError("truncated tensor payload")
    arr = np.frombuffer(data[start:end], dtype=meta.type.np_dtype).reshape(
        meta.to_info().shape
    )
    return arr, end
