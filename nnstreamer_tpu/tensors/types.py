"""Tensor type system: dtypes, dimensions, formats, and stream configs.

Capability parity with the reference's tensor type system
(``gst/nnstreamer/include/tensor_typedef.h:153-297`` and the caps/config
helpers in ``gst/nnstreamer/tensor_common.c``), re-designed for a JAX/XLA
runtime:

- the ten reference dtypes plus TPU-native ``float16``/``bfloat16``;
- per-frame multi-tensor streams (up to ``NNS_TENSOR_SIZE_LIMIT`` tensors);
- three stream formats: ``STATIC`` (shapes fixed by caps), ``FLEXIBLE``
  (per-buffer self-describing header, see ``tensors.meta``) and ``SPARSE``
  (COO payloads, see ``elements.sparse``);
- caps-string serialization compatible in spirit with the reference's
  ``other/tensors,num_tensors=..,dimensions=..,types=..`` negotiation
  grammar so pipelines negotiate the same way.

Dimension convention: like the reference, a ``dim`` tuple is innermost-first
(``(C, W, H, N)`` for video), while :meth:`TensorInfo.shape` gives the
row-major numpy/JAX shape (``(N, H, W, C)``). Keeping the reference's caps
grammar costs nothing at runtime — shapes are static by the time XLA sees
them.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

#: Maximum rank of a single tensor (reference: 4→8→16 over versions; we use 8,
#: which covers every model family in scope and keeps caps strings readable).
NNS_TENSOR_RANK_LIMIT = 8

#: Maximum number of tensors in one stream frame (reference:
#: ``NNS_TENSOR_SIZE_LIMIT == 16``, tensor_typedef.h:38).
NNS_TENSOR_SIZE_LIMIT = 16

#: Caps media-type names (reference: ``other/tensor`` / ``other/tensors``).
MEDIA_TENSOR = "other/tensor"
MEDIA_TENSORS = "other/tensors"


class TensorType(enum.Enum):
    """Element dtype of a tensor (reference ``tensor_type``,
    tensor_typedef.h:153-168, plus TPU-native half types)."""

    INT32 = "int32"
    UINT32 = "uint32"
    INT16 = "int16"
    UINT16 = "uint16"
    INT8 = "int8"
    UINT8 = "uint8"
    FLOAT64 = "float64"
    FLOAT32 = "float32"
    INT64 = "int64"
    UINT64 = "uint64"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"

    @property
    def np_dtype(self) -> np.dtype:
        if self is TensorType.BFLOAT16:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(self.value)

    @property
    def size(self) -> int:
        """Bytes per element."""
        return self.np_dtype.itemsize

    @classmethod
    def from_any(cls, value) -> "TensorType":
        """Coerce a string / numpy dtype / jax dtype / TensorType."""
        if isinstance(value, TensorType):
            return value
        if isinstance(value, str):
            return cls(value.lower())
        name = np.dtype(value).name
        if name == "bfloat16":
            return cls.BFLOAT16
        return cls(name)


class TensorFormat(enum.Enum):
    """Stream data format (reference ``tensor_format``,
    tensor_typedef.h:192-199)."""

    STATIC = "static"
    FLEXIBLE = "flexible"
    SPARSE = "sparse"

    @classmethod
    def from_any(cls, value) -> "TensorFormat":
        if isinstance(value, TensorFormat):
            return value
        return cls(str(value).lower())


def _parse_dim(text: str) -> Tuple[int, ...]:
    """Parse ``"3:224:224:1"`` into an innermost-first dim tuple."""
    parts = [p for p in text.strip().split(":") if p != ""]
    if not parts:
        raise ValueError(f"empty dimension string: {text!r}")
    if len(parts) > NNS_TENSOR_RANK_LIMIT:
        raise ValueError(
            f"rank {len(parts)} exceeds limit {NNS_TENSOR_RANK_LIMIT}: {text!r}"
        )
    dim = tuple(int(p) for p in parts)
    if any(d < 1 for d in dim):
        raise ValueError(f"dimensions must be >= 1: {text!r}")
    return dim


def _dim_to_str(dim: Sequence[int]) -> str:
    return ":".join(str(d) for d in dim)


def _trim_dim(dim: Sequence[int]) -> Tuple[int, ...]:
    """Drop trailing 1s (ranks compare equal modulo trailing 1s, like the
    reference's ``gst_tensor_dimension_is_equal``)."""
    dim = tuple(dim)
    while len(dim) > 1 and dim[-1] == 1:
        dim = dim[:-1]
    return dim


@dataclasses.dataclass
class TensorInfo:
    """Shape+dtype (+optional name) of one tensor in a frame.

    Reference: ``GstTensorInfo`` (tensor_typedef.h:239-247).
    """

    dim: Tuple[int, ...] = ()
    type: Optional[TensorType] = None
    name: Optional[str] = None

    def __post_init__(self):
        self.dim = tuple(int(d) for d in self.dim)
        if self.type is not None:
            self.type = TensorType.from_any(self.type)
        if len(self.dim) > NNS_TENSOR_RANK_LIMIT:
            raise ValueError(f"rank {len(self.dim)} exceeds {NNS_TENSOR_RANK_LIMIT}")

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_array(cls, arr, name: Optional[str] = None) -> "TensorInfo":
        """Build from a numpy/jax array: shape is reversed into dim order."""
        return cls(
            dim=tuple(reversed(arr.shape)) if arr.ndim else (1,),
            type=TensorType.from_any(arr.dtype),
            name=name,
        )

    @classmethod
    def from_str(cls, dim_str: str, type_str: str, name: Optional[str] = None):
        return cls(dim=_parse_dim(dim_str), type=TensorType(type_str), name=name)

    # -- derived -----------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        """Row-major (numpy/JAX) shape — reversed dim order."""
        return tuple(reversed(self.dim))

    @property
    def num_elements(self) -> int:
        return int(math.prod(self.dim)) if self.dim else 0

    @property
    def size(self) -> int:
        """Byte size of one tensor (reference ``gst_tensor_info_get_size``)."""
        if self.type is None or not self.dim:
            return 0
        return self.num_elements * self.type.size

    def is_valid(self) -> bool:
        return self.type is not None and bool(self.dim) and all(
            d >= 1 for d in self.dim
        )

    def is_equal(self, other: "TensorInfo") -> bool:
        """Dim/type equality modulo trailing 1s (names ignored, like the
        reference's ``gst_tensor_info_is_equal``)."""
        return (
            self.type == other.type
            and _trim_dim(self.dim) == _trim_dim(other.dim)
        )

    def dim_str(self) -> str:
        return _dim_to_str(self.dim)

    def __repr__(self):
        t = self.type.value if self.type else "?"
        n = f" name={self.name!r}" if self.name else ""
        return f"TensorInfo({self.dim_str()} {t}{n})"


@dataclasses.dataclass
class TensorsInfo:
    """Info for every tensor in a frame (reference ``GstTensorsInfo``,
    tensor_typedef.h:249-257)."""

    infos: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if len(self.infos) > NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(
                f"{len(self.infos)} tensors exceeds {NNS_TENSOR_SIZE_LIMIT}"
            )

    @classmethod
    def from_arrays(cls, arrays: Iterable) -> "TensorsInfo":
        return cls([TensorInfo.from_array(a) for a in arrays])

    @classmethod
    def from_str(cls, dims: str, types: str, names: str = "") -> "TensorsInfo":
        dim_list = [d for d in dims.split(",") if d.strip()]
        type_list = [t.strip() for t in types.split(",") if t.strip()]
        name_list = [n.strip() for n in names.split(",")] if names else []
        if len(dim_list) != len(type_list):
            raise ValueError(
                f"dimensions/types count mismatch: {dims!r} vs {types!r}"
            )
        out = []
        for i, (d, t) in enumerate(zip(dim_list, type_list)):
            name = name_list[i] if i < len(name_list) and name_list[i] else None
            out.append(TensorInfo.from_str(d, t, name))
        return cls(out)

    # -- container protocol -------------------------------------------------
    def __len__(self):
        return len(self.infos)

    def __getitem__(self, i) -> TensorInfo:
        return self.infos[i]

    def __iter__(self):
        return iter(self.infos)

    def append(self, info: TensorInfo):
        if len(self.infos) >= NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(f"cannot exceed {NNS_TENSOR_SIZE_LIMIT} tensors")
        self.infos.append(info)

    # -- derived ------------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.infos)

    def is_valid(self) -> bool:
        return bool(self.infos) and all(i.is_valid() for i in self.infos)

    def is_equal(self, other: "TensorsInfo") -> bool:
        return len(self) == len(other) and all(
            a.is_equal(b) for a, b in zip(self.infos, other.infos)
        )

    def dims_str(self) -> str:
        return ",".join(i.dim_str() for i in self.infos)

    def types_str(self) -> str:
        return ",".join(i.type.value if i.type else "?" for i in self.infos)

    def total_size(self) -> int:
        return sum(i.size for i in self.infos)

    def __repr__(self):
        return f"TensorsInfo([{', '.join(map(repr, self.infos))}])"


@dataclasses.dataclass
class Fraction:
    """Framerate as an exact fraction (reference caps use GstFraction)."""

    num: int = 0
    den: int = 1

    def __post_init__(self):
        if self.den == 0:
            raise ValueError("framerate denominator must be nonzero")
        g = math.gcd(int(self.num), int(self.den)) or 1
        self.num, self.den = int(self.num) // g, int(self.den) // g

    @classmethod
    def parse(cls, text) -> "Fraction":
        if isinstance(text, Fraction):
            return text
        if isinstance(text, (int, float)):
            return cls(int(text), 1)
        if "/" in text:
            n, d = text.split("/", 1)
            return cls(int(n), int(d))
        return cls(int(text), 1)

    @property
    def fps(self) -> float:
        return self.num / self.den if self.den else 0.0

    @property
    def frame_duration_ns(self) -> Optional[int]:
        if self.num <= 0:
            return None
        return int(round(1e9 * self.den / self.num))

    def __str__(self):
        return f"{self.num}/{self.den}"


@dataclasses.dataclass
class TensorsConfig:
    """Full stream configuration: tensor infos + format + rate.

    Reference: ``GstTensorsConfig`` (tensor_typedef.h:262-270). This is the
    payload of caps negotiation between elements.
    """

    info: TensorsInfo = dataclasses.field(default_factory=TensorsInfo)
    format: TensorFormat = TensorFormat.STATIC
    rate: Fraction = dataclasses.field(default_factory=lambda: Fraction(0, 1))

    @classmethod
    def from_arrays(cls, arrays, rate=None) -> "TensorsConfig":
        return cls(
            info=TensorsInfo.from_arrays(arrays),
            rate=Fraction.parse(rate) if rate is not None else Fraction(0, 1),
        )

    def is_valid(self) -> bool:
        if self.format in (TensorFormat.FLEXIBLE, TensorFormat.SPARSE):
            return True  # shapes are per-buffer (self-describing headers)
        return self.info.is_valid()

    def is_equal(self, other: "TensorsConfig") -> bool:
        if self.format != other.format:
            return False
        if self.format is TensorFormat.STATIC:
            return self.info.is_equal(other.info)
        return True

    # -- caps serialization -------------------------------------------------
    def to_caps(self) -> "Caps":
        from nnstreamer_tpu.pipeline.caps import Caps

        fields = {"format": self.format.value}
        if self.format is TensorFormat.STATIC and self.info.num_tensors:
            fields["num_tensors"] = self.info.num_tensors
            fields["dimensions"] = self.info.dims_str()
            fields["types"] = self.info.types_str()
        if self.rate.num > 0:
            fields["framerate"] = str(self.rate)
        return Caps(MEDIA_TENSORS, fields)

    @classmethod
    def from_caps(cls, caps) -> "TensorsConfig":
        if caps.name not in (MEDIA_TENSOR, MEDIA_TENSORS):
            raise ValueError(f"not a tensor caps: {caps.name}")
        fmt = TensorFormat.from_any(caps.get("format", "static"))
        info = TensorsInfo()
        if "dimensions" in caps and "types" in caps:
            info = TensorsInfo.from_str(
                str(caps["dimensions"]), str(caps["types"]), str(caps.get("names", ""))
            )
        rate = Fraction.parse(caps.get("framerate", "0/1"))
        return cls(info=info, format=fmt, rate=rate)

    def __repr__(self):
        return (
            f"TensorsConfig({self.info!r}, format={self.format.value}, "
            f"rate={self.rate})"
        )
