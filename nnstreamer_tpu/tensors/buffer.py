"""TensorBuffer — one stream frame: N tensors + timing metadata.

The reference flows ``GstBuffer``s holding up to 16 ``GstMemory`` chunks
(one per tensor) with pts/dts/duration and attachable metas
(``gst/nnstreamer/tensor_meta.c``). Here a frame is a list of *arrays* —
host ``numpy.ndarray`` or device-resident ``jax.Array`` — so tensors can stay
in TPU HBM as they flow between elements (the reference's zero-copy
``GstMemory`` mapping, ``tensor_filter.c:585-604``, maps to "never leave the
device"). Host/device placement is explicit via :meth:`to_device` /
:meth:`to_host`; elements that only reorder/route tensors never touch bytes.

``meta`` carries attachable per-buffer metadata the way GstMeta does — e.g.
the query client id used by the distributed serversink to route results
(reference ``GstMetaQuery``, tensor_meta.c), or crop regions.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.tensors.types import (
    NNS_TENSOR_SIZE_LIMIT,
    TensorsInfo,
)

#: Sentinel for "no timestamp" (reference GST_CLOCK_TIME_NONE).
CLOCK_NONE: Optional[int] = None


def residency_enabled() -> bool:
    """Global off-switch for the device-residency layer. With
    ``NNSTPU_RESIDENT=0`` no :class:`DeviceBuffer` is ever created and
    every element sees plain host-materialized buffers, which is the
    byte-equality reference the residency tests compare against."""
    return os.environ.get("NNSTPU_RESIDENT", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )


# -- transfer accounting ------------------------------------------------------
# Process-wide tallies of explicit host<->device copies plus the pad-entry
# residency split, mirrored into obs/ as nns_transfer_h2d_bytes_total /
# nns_transfer_d2h_bytes_total counters and the nns_buffer_resident_ratio
# gauge. bench.py reads transfer_snapshot() deltas per run (d2h_per_frame).
_xfer_lock = threading.Lock()
_xfer: Dict[str, float] = {
    "h2d_bytes": 0.0, "h2d_events": 0.0,
    "d2h_bytes": 0.0, "d2h_events": 0.0,
    # staged multi-frame window transfers (one device_put / device_get
    # covering a whole dispatch window): *_events counts uploads/fetches,
    # *_frames the frames they carried. Per-frame h2d_events/d2h_events
    # deliberately do NOT move for these — d2h_per_frame / h2d_per_frame
    # measure per-frame round trips, which window batching exists to
    # drive to zero (the bytes still land in h2d_bytes/d2h_bytes).
    "h2d_batched_events": 0.0, "h2d_batched_frames": 0.0,
    "d2h_batched_events": 0.0, "d2h_batched_frames": 0.0,
    "resident_entries": 0.0, "materialized_entries": 0.0,
}
_xfer_metrics: Optional[Dict[str, Any]] = None


def _xfer_obs() -> Dict[str, Any]:
    global _xfer_metrics
    if _xfer_metrics is None:
        from nnstreamer_tpu.obs import get_registry

        reg = get_registry()
        _xfer_metrics = {
            "h2d": reg.counter(
                "nns_transfer_h2d_bytes_total",
                "Bytes explicitly uploaded host->device "
                "(TensorBuffer.to_device)"),
            "d2h": reg.counter(
                "nns_transfer_d2h_bytes_total",
                "Bytes explicitly materialized device->host (to_host)"),
            "h2d_batched": reg.counter(
                "nns_transfer_batched_h2d_total",
                "Staged multi-frame slab uploads: one device_put "
                "carrying a whole dispatch window (upload_many)"),
            "d2h_batched": reg.counter(
                "nns_transfer_batched_d2h_total",
                "Grouped drain-side fetches: one device_get carrying a "
                "whole materialization run (materialize_many)"),
        }
        reg.gauge(
            "nns_buffer_resident_ratio",
            "Fraction of DeviceBuffer pad entries forwarded without host "
            "materialization",
            fn=lambda: resident_ratio() or 0.0)
    return _xfer_metrics


def _record_h2d(nbytes: int) -> None:
    if nbytes <= 0:
        return
    _xfer_obs()["h2d"].inc(nbytes)
    with _xfer_lock:
        _xfer["h2d_bytes"] += nbytes
        _xfer["h2d_events"] += 1


def _record_d2h(nbytes: int) -> None:
    if nbytes <= 0:
        return
    _xfer_obs()["d2h"].inc(nbytes)
    with _xfer_lock:
        _xfer["d2h_bytes"] += nbytes
        _xfer["d2h_events"] += 1


def _record_h2d_batched(frames: int, nbytes: int) -> None:
    """One staged multi-frame slab upload: bytes land in the cumulative
    h2d byte tally, but the per-frame event counter does not move — the
    whole point of the window slab is that these frames paid no
    per-frame round trip."""
    if nbytes <= 0:
        return
    obs = _xfer_obs()
    obs["h2d"].inc(nbytes)
    obs["h2d_batched"].inc()
    with _xfer_lock:
        _xfer["h2d_bytes"] += nbytes
        _xfer["h2d_batched_events"] += 1
        _xfer["h2d_batched_frames"] += frames


def _record_d2h_batched(frames: int, nbytes: int) -> None:
    if nbytes <= 0:
        return
    obs = _xfer_obs()
    obs["d2h"].inc(nbytes)
    obs["d2h_batched"].inc()
    with _xfer_lock:
        _xfer["d2h_bytes"] += nbytes
        _xfer["d2h_batched_events"] += 1
        _xfer["d2h_batched_frames"] += frames


def _tl_xfer_span(kind: str, meta: Dict[str, Any], t0: float,
                  nbytes: int = 0) -> None:
    """Record a transfer span (``h2d``/``d2h``) on the active timeline
    for the frame carried in ``meta`` — free single-test no-op when
    tracing is off or the buffer predates the source's seq stamp."""
    tl = _timeline.ACTIVE
    if tl is None:
        return
    seq = meta.get(_timeline.TRACE_SEQ_META)
    if seq is None:
        return
    tl.span(kind, seq, t0, time.monotonic(), track="transfer",
            nbytes=nbytes)


def _fault_check(site: str, meta: Dict[str, Any]) -> None:
    """Transfer-site chaos hook (pipeline/faults.py), resolved through
    ``sys.modules`` so the tensors layer never imports the pipeline
    package (element.py imports this module — a top-level import back
    would cycle). With injection off this is one dict lookup; an
    injector can only exist once its module is imported, so the lazy
    resolution can never miss an active one."""
    import sys

    faults = sys.modules.get("nnstreamer_tpu.pipeline.faults")
    if faults is None or faults.ACTIVE is None:
        return
    faults.ACTIVE.check(site, seq=meta.get(_timeline.TRACE_SEQ_META))


def _mem_note_h2d(nbytes: int, owner) -> None:
    """Register an H2D transfer's bytes with the HBM budget accountant
    (``tensors/memory.py``); ``owner`` is the Python buffer wrapper whose
    death releases the device payload, so the accountant's ``frames``
    category tracks the live device working set. Same ``sys.modules``
    kill-switch shape as :func:`_fault_check`: no accountant, one dict
    lookup, out."""
    import sys

    mem = sys.modules.get("nnstreamer_tpu.tensors.memory")
    if mem is None or mem.ACTIVE is None:
        return
    mem.ACTIVE.note_h2d(nbytes, owner)


def record_residency_entry(resident: bool) -> None:
    """Tally one DeviceBuffer pad entry: ``resident`` means the element
    declared DEVICE_PASSTHROUGH and the buffer crossed the pad without a
    host copy (the numerator of ``nns_buffer_resident_ratio``)."""
    _xfer_obs()  # the gauge is registered with the counters
    with _xfer_lock:
        key = "resident_entries" if resident else "materialized_entries"
        _xfer[key] += 1


def resident_ratio() -> Optional[float]:
    with _xfer_lock:
        r = _xfer["resident_entries"]
        m = _xfer["materialized_entries"]
    total = r + m
    return (r / total) if total else None


def transfer_snapshot() -> Dict[str, float]:
    """Copy of the cumulative transfer tallies (bytes + event counts +
    entry split); callers diff two snapshots for per-run numbers."""
    with _xfer_lock:
        return dict(_xfer)


def _device_nbytes(t) -> int:
    return int(np.prod(t.shape, dtype=np.int64)) * np.dtype(t.dtype).itemsize


import functools


@functools.lru_cache(maxsize=256)
def _pad_rows_fn(r: int, shape: tuple, dtype: str):
    """Jitted axis-0 zero-pad, cached per (pad, shape, dtype) so each
    partial-window size costs one small compile, then one fused device
    dispatch per tensor (see TensorBuffer.pad_rows_device)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.concatenate(
            [x, jnp.zeros((r,) + tuple(shape[1:]), x.dtype)], axis=0)

    return f


def is_device_array(x) -> bool:
    """True if ``x`` is a jax.Array (device-resident)."""
    import jax

    return isinstance(x, jax.Array)


def _host_owned(t) -> np.ndarray:
    """D2H that OWNS its bytes. ``np.asarray`` on a CPU jax array can be
    a zero-copy view into the XLA buffer; once that buffer is released
    (dispatch-window fence) and its memory reused, the view silently
    reads the NEXT tenant's bytes. Donating fused programs make this
    real: a persistent-cache-deserialized executable keeps its
    input-output aliasing (the in-process compile drops it for host
    inputs), so warm-boot outputs live in donated slabs with exactly
    that lifetime. Real accelerators already return owning arrays here,
    so the copy triggers only where the aliasing hazard exists."""
    v = np.asarray(t)
    if v.base is not None or not v.flags.owndata:
        v = np.array(v)  # defensive copy: detach from the XLA buffer
    return v


@dataclasses.dataclass
class TensorBuffer:
    """One frame of a tensor stream.

    Attributes
    ----------
    tensors : list of numpy.ndarray or jax.Array
    pts, dts, duration : int nanoseconds, or None (unset)
    meta : free-form attachable metadata (GstMeta equivalent)
    """

    tensors: List[Any] = dataclasses.field(default_factory=list)
    pts: Optional[int] = None
    dts: Optional[int] = None
    duration: Optional[int] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: deferred host-side completion: ``fn(host_buf) -> TensorBuffer``,
    #: applied by :meth:`to_host` after tensors materialize. Lets a fused
    #: region keep a decoder's math on device (argmax, box select) and
    #: delay its host-only part (label strings, overlay compose) to the
    #: sink's fetch point — so no element forces a blocking D2H mid-stream.
    finalize: Optional[Any] = None

    def __post_init__(self):
        if len(self.tensors) > NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(
                f"{len(self.tensors)} tensors exceeds {NNS_TENSOR_SIZE_LIMIT}"
            )

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_arrays(cls, arrays: Sequence, pts: Optional[int] = None, **kw):
        return cls(tensors=list(arrays), pts=pts, **kw)

    @classmethod
    def wall_clock_pts(cls) -> int:
        return time.monotonic_ns()

    # -- container protocol --------------------------------------------------
    def __len__(self):
        return len(self.tensors)

    def __getitem__(self, i):
        return self.tensors[i]

    def __iter__(self):
        return iter(self.tensors)

    # -- derived -------------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def tensors_info(self) -> TensorsInfo:
        return TensorsInfo.from_arrays(self.tensors)

    def nbytes(self) -> int:
        return sum(int(np.prod(t.shape)) * t.dtype.itemsize for t in self.tensors)

    def create_stamps(self):
        """Capture timestamps carried in meta for end-to-end latency:
        the plural ``create_ts`` (aggregated/muxed frames, one stamp per
        constituent frame) or the singular ``create_t`` a source
        stamped. Returns a (possibly empty) list."""
        stamps = self.meta.get("create_ts")
        if stamps:
            return list(stamps)
        if "create_t" in self.meta:
            return [self.meta["create_t"]]
        return []

    def on_device(self) -> bool:
        return bool(self.tensors) and all(is_device_array(t) for t in self.tensors)

    # -- placement -----------------------------------------------------------
    def to_host(self) -> "TensorBuffer":
        """Materialize all tensors as numpy arrays (blocking D2H if needed),
        then apply the deferred ``finalize`` hook if one is attached."""
        t0 = time.monotonic()
        out, moved = [], 0
        for t in self.tensors:
            if isinstance(t, np.ndarray):
                out.append(t)
            else:
                out.append(_host_owned(t))
                moved += _device_nbytes(t)
        if moved:
            _fault_check("transfer.d2h", self.meta)
            _record_d2h(moved)
            _tl_xfer_span("d2h", self.meta, t0, nbytes=moved)
        buf = self.replace(tensors=out, finalize=None)
        if self.finalize is not None:
            buf = self.finalize(buf)
        return buf

    def to_device(self, device=None, sharding=None) -> "TensorBuffer":
        """Move all tensors onto a JAX device (or sharding)."""
        import jax

        tgt = sharding if sharding is not None else device
        t0 = time.monotonic()
        moved = sum(_device_nbytes(t) for t in self.tensors
                    if not is_device_array(t))
        out = [jax.device_put(t, tgt) if tgt is not None else jax.device_put(t)
               for t in self.tensors]
        buf = self.replace(tensors=out)
        if moved:
            _fault_check("transfer.h2d", self.meta)
            _record_h2d(moved)
            _tl_xfer_span("h2d", self.meta, t0, nbytes=moved)
            _mem_note_h2d(moved, buf)
        return buf

    def pad_rows_device(self) -> "TensorBuffer":
        """Apply a deferred partial-window pad (aggregator
        ``pad-device``): zero-pad ``meta["pad_rows"]`` leading-axis rows
        onto each (device-resident) tensor with one tiny jitted program
        per (shape, pad) — the pad rows never cross the H2D link, and
        the downstream jitted consumer keeps its single full-window
        compiled shape. No-op without the meta key."""
        r = self.meta.get("pad_rows")
        if not r:
            return self
        out = [_pad_rows_fn(int(r), t.shape, str(t.dtype))(t)
               for t in self.tensors]
        meta = dict(self.meta)
        del meta["pad_rows"]
        return self.replace(tensors=out, meta=meta)

    def block_until_ready(self) -> "TensorBuffer":
        for t in self.tensors:
            if is_device_array(t):
                t.block_until_ready()
        return self

    # -- functional update ----------------------------------------------------
    def replace(self, **kw) -> "TensorBuffer":
        """Copy with replaced fields; tensors list is shallow-copied, meta is
        copied (buffers are treated as immutable once pushed)."""
        fields = dict(
            tensors=list(self.tensors),
            pts=self.pts,
            dts=self.dts,
            duration=self.duration,
            meta=dict(self.meta),
            finalize=self.finalize,
        )
        fields.update(kw)
        return TensorBuffer(**fields)

    def with_tensors(self, tensors: Sequence) -> "TensorBuffer":
        """New buffer with the same timing/meta but different payload."""
        return self.replace(tensors=list(tensors))

    def __repr__(self):
        shapes = ",".join(
            f"{tuple(t.shape)}:{np.dtype(t.dtype).name}" for t in self.tensors
        )
        dev = "dev" if self.on_device() else "host"
        return f"TensorBuffer([{shapes}] {dev} pts={self.pts})"


# -- device residency ---------------------------------------------------------
def _unpin_tokens(tokens) -> None:
    """weakref.finalize target for a dead DeviceBuffer's pinned host-view
    slabs (module-level so the finalizer holds no reference to the buffer)."""
    from nnstreamer_tpu.tensors.pool import get_pool

    pool = get_pool()
    for t in tokens:
        pool.unpin(t)


class DeviceBuffer(TensorBuffer):
    """A device-resident frame: live ``jax.Array`` payloads that cross pad
    boundaries without touching the host.

    Elements that declare ``DEVICE_PASSTHROUGH`` forward these untouched;
    everything else gets a host-materialized copy at pad entry (see
    ``Element._chain_entry``). The host side is *lazy and cached*:

    - the first :meth:`to_host` call is the one sanctioned D2H site (lint
      NNS108) — it materializes once, applies ``finalize``, and caches;
      every later call returns the SAME host buffer object;
    - a ``host_view`` — the pre-upload host arrays a prefetching queue
      already holds — makes that first call a zero-copy re-wrap. Pool-owned
      host-view arrays are *pinned* so an explicit ``BufferPool.release``
      (sink/dispatch fence) can never recycle a slab this cache still
      reads; the pin lifts when the wrapper itself dies.
    """

    def __init__(self, tensors=None, pts=None, dts=None, duration=None,
                 meta=None, finalize=None, host_view=None):
        super().__init__(tensors=list(tensors or []), pts=pts, dts=dts,
                         duration=duration, meta=dict(meta or {}),
                         finalize=finalize)
        self._host_cache: Optional[TensorBuffer] = None
        self._host_src: Optional[List[Any]] = None
        if host_view is not None and len(host_view) == len(self.tensors):
            self._adopt_host_view(list(host_view))

    def _adopt_host_view(self, host: List[Any]) -> None:
        from nnstreamer_tpu.tensors.pool import get_pool

        self._host_src = host
        pool = get_pool()
        tokens = tuple(id(a) for a in host if pool.pin(a))
        if tokens:
            weakref.finalize(self, _unpin_tokens, tokens)

    def to_host(self) -> TensorBuffer:
        """The sanctioned materialization point: one D2H (or zero, when a
        pre-upload host view was adopted), finalize applied once, result
        cached and shared by every later caller."""
        cached = self._host_cache
        if cached is not None:
            return cached
        if self._host_src is not None:
            host = list(self._host_src)  # zero-copy: pre-upload bytes
        else:
            t0 = time.monotonic()
            host, moved = [], 0
            for t in self.tensors:
                if isinstance(t, np.ndarray):
                    host.append(t)
                else:
                    host.append(_host_owned(t))
                    moved += _device_nbytes(t)
            if moved:
                _fault_check("transfer.d2h", self.meta)
                _record_d2h(moved)
                _tl_xfer_span("d2h", self.meta, t0, nbytes=moved)
        buf = TensorBuffer(tensors=host, pts=self.pts, dts=self.dts,
                           duration=self.duration, meta=dict(self.meta),
                           finalize=None)
        if self.finalize is not None:
            buf = self.finalize(buf)
        self._host_cache = buf
        return buf

    def replace(self, **kw) -> TensorBuffer:
        """Stays a :class:`DeviceBuffer` while the payload stays on device
        (so routing elements' ``replace()``/``with_tensors()`` don't
        silently demote residency); an unchanged payload keeps the adopted
        host view. The materialized-host cache is never carried over —
        meta/finalize edits would make it stale."""
        fields = dict(
            tensors=list(self.tensors),
            pts=self.pts,
            dts=self.dts,
            duration=self.duration,
            meta=dict(self.meta),
            finalize=self.finalize,
        )
        fields.update(kw)
        tensors = fields["tensors"]
        if tensors and all(is_device_array(t) for t in tensors):
            host_view = self._host_src if "tensors" not in kw else None
            return DeviceBuffer(host_view=host_view, **fields)
        return TensorBuffer(**fields)

    def __repr__(self):
        base = super().__repr__()
        state = ("view" if self._host_src is not None else
                 "cached" if self._host_cache is not None else "lazy")
        return base.replace("TensorBuffer(", f"DeviceBuffer(host={state} ", 1)


def as_device_buffer(buf: TensorBuffer, host_view=None) -> TensorBuffer:
    """Wrap an all-device buffer as a :class:`DeviceBuffer`; returns the
    input unchanged when residency is disabled, the payload is not fully
    on device, or it is already wrapped."""
    if isinstance(buf, DeviceBuffer) or not residency_enabled():
        return buf
    if not buf.on_device():
        return buf
    return DeviceBuffer(tensors=buf.tensors, pts=buf.pts, dts=buf.dts,
                        duration=buf.duration, meta=buf.meta,
                        finalize=buf.finalize, host_view=host_view)


# -- staged multi-frame window transfers --------------------------------------
#: meta key marking a buffer whose device payload was freshly created by
#: an upload point for exactly one downstream consumer — the whole-graph
#: fused region may DONATE such tensors to XLA (pipeline/fuse.py); shared
#: or source-owned payloads never carry it
H2D_EXCLUSIVE_META = "h2d_exclusive"


def upload_many(bufs: List[TensorBuffer]) -> (
        "tuple[List[TensorBuffer], List[np.ndarray]]"):
    """Coalesce one dispatch window's H2D copies into a single staged
    multi-frame slab upload (FaaSTube-style transfer batching).

    For each tensor index the window's frames are assembled into ONE
    contiguous ``(k,) + shape`` host view — zero-copy when the frames are
    already consecutive window-slab slots (``pool.contiguous_window_view``,
    the ingest-lane staging layout), else copied into a fresh pool window
    slab — and cross the link as ONE ``jax.device_put``. Per-frame device
    views are carved device-side (a lazy slice per slot, no extra
    transfers). Returns ``(device_buffers, window_slabs)``: the caller
    stamps the slabs into the LAST buffer's pool stash so the dispatch
    window's fence (``pipeline/dispatch.py``) recycles them only after
    every dispatch that read the upload has completed.

    Callers must pass ≥1 host-resident buffers with identical tensor
    signatures; ordering and per-buffer meta/finalize are preserved, so
    results are byte-identical to per-buffer ``to_device()``.
    """
    import jax

    from nnstreamer_tpu.tensors.pool import (
        contiguous_window_view,
        get_pool,
    )

    k = len(bufs)
    n_t = len(bufs[0].tensors)
    pool = get_pool()
    t0 = time.monotonic()
    _fault_check("transfer.h2d", bufs[0].meta)
    slabs: List[np.ndarray] = []
    stacked_per_tensor: List[np.ndarray] = []
    moved = 0
    for j in range(n_t):
        frames = [b.tensors[j] for b in bufs]
        stacked = contiguous_window_view(frames) if k > 1 else None
        if stacked is None:
            stacked = pool.acquire_window(k, frames[0].shape,
                                          frames[0].dtype)
            for i, f in enumerate(frames):
                np.copyto(stacked[i], f)
            slabs.append(stacked)
        moved += stacked.nbytes
        stacked_per_tensor.append(stacked)
    devs = [jax.device_put(s) for s in stacked_per_tensor]
    _record_h2d_batched(k, moved)
    _tl_xfer_span("h2d_batched", bufs[0].meta, t0, nbytes=moved)
    out: List[TensorBuffer] = []
    for i, b in enumerate(bufs):
        dev_tensors = [devs[j][i] for j in range(n_t)]
        nb = b.with_tensors(dev_tensors)
        nb.meta[H2D_EXCLUSIVE_META] = True
        # the pre-upload host arrays become the wrapper's zero-copy host
        # view, exactly like the per-buffer prefetch path
        wrapped = as_device_buffer(nb, host_view=list(b.tensors))
        # each frame view shares the window's device slabs; the budget
        # accountant sees a per-frame share so the frames category tracks
        # the live working set as views die
        _mem_note_h2d(moved // k, wrapped)
        out.append(wrapped)
    return out, slabs


def materialize_many(bufs: List[TensorBuffer]) -> List[TensorBuffer]:
    """Drain-side grouped materialization: every device tensor across the
    run crosses D2H in ONE ``jax.device_get`` instead of one blocking
    fetch per frame. Results are byte-identical to calling ``to_host()``
    per buffer — per-buffer ``finalize`` hooks run in order on the host
    payloads, DeviceBuffer host caches are honored and filled — but the
    transfer tally records one *batched* fetch (``d2h_batched_events``)
    and zero per-frame round trips, which is what ``d2h_per_frame = 0``
    on a device-decodable pipeline means."""
    import jax

    fetch: List[Any] = []
    where: Dict[Any, int] = {}
    direct: List[bool] = []
    for i, b in enumerate(bufs):
        if isinstance(b, DeviceBuffer) and (
                b._host_cache is not None or b._host_src is not None):
            direct.append(True)  # zero-copy/cached: to_host() is free
            continue
        direct.append(False)
        for j, t in enumerate(b.tensors):
            if not isinstance(t, np.ndarray):
                where[(i, j)] = len(fetch)
                fetch.append(t)
    if fetch:
        t0 = time.monotonic()
        moved = sum(_device_nbytes(t) for t in fetch)
        _fault_check("transfer.d2h", bufs[0].meta)
        # the one sanctioned *batched* D2H: a single grouped fetch for
        # the whole run  # nns-lint: disable=NNS108 -- batched twin of to_host
        fetched = jax.device_get(fetch)
        _record_d2h_batched(len(bufs), moved)
        _tl_xfer_span("d2h_batched", bufs[0].meta, t0, nbytes=moved)
    out: List[TensorBuffer] = []
    for i, b in enumerate(bufs):
        if direct[i] or not any((i, j) in where
                                for j in range(len(b.tensors))):
            out.append(b.to_host())  # cached view or already-host payload
            continue
        host = [t if isinstance(t, np.ndarray)
                else np.asarray(fetched[where[(i, j)]])
                for j, t in enumerate(b.tensors)]
        hb = TensorBuffer(tensors=host, pts=b.pts, dts=b.dts,
                          duration=b.duration, meta=dict(b.meta),
                          finalize=None)
        if b.finalize is not None:
            hb = b.finalize(hb)
        if isinstance(b, DeviceBuffer):
            b._host_cache = hb  # later to_host() callers share this
        out.append(hb)
    return out
