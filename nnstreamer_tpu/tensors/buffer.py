"""TensorBuffer — one stream frame: N tensors + timing metadata.

The reference flows ``GstBuffer``s holding up to 16 ``GstMemory`` chunks
(one per tensor) with pts/dts/duration and attachable metas
(``gst/nnstreamer/tensor_meta.c``). Here a frame is a list of *arrays* —
host ``numpy.ndarray`` or device-resident ``jax.Array`` — so tensors can stay
in TPU HBM as they flow between elements (the reference's zero-copy
``GstMemory`` mapping, ``tensor_filter.c:585-604``, maps to "never leave the
device"). Host/device placement is explicit via :meth:`to_device` /
:meth:`to_host`; elements that only reorder/route tensors never touch bytes.

``meta`` carries attachable per-buffer metadata the way GstMeta does — e.g.
the query client id used by the distributed serversink to route results
(reference ``GstMetaQuery``, tensor_meta.c), or crop regions.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.tensors.types import (
    NNS_TENSOR_SIZE_LIMIT,
    TensorsInfo,
)

#: Sentinel for "no timestamp" (reference GST_CLOCK_TIME_NONE).
CLOCK_NONE: Optional[int] = None


import functools


@functools.lru_cache(maxsize=256)
def _pad_rows_fn(r: int, shape: tuple, dtype: str):
    """Jitted axis-0 zero-pad, cached per (pad, shape, dtype) so each
    partial-window size costs one small compile, then one fused device
    dispatch per tensor (see TensorBuffer.pad_rows_device)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return jnp.concatenate(
            [x, jnp.zeros((r,) + tuple(shape[1:]), x.dtype)], axis=0)

    return f


def is_device_array(x) -> bool:
    """True if ``x`` is a jax.Array (device-resident)."""
    import jax

    return isinstance(x, jax.Array)


@dataclasses.dataclass
class TensorBuffer:
    """One frame of a tensor stream.

    Attributes
    ----------
    tensors : list of numpy.ndarray or jax.Array
    pts, dts, duration : int nanoseconds, or None (unset)
    meta : free-form attachable metadata (GstMeta equivalent)
    """

    tensors: List[Any] = dataclasses.field(default_factory=list)
    pts: Optional[int] = None
    dts: Optional[int] = None
    duration: Optional[int] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: deferred host-side completion: ``fn(host_buf) -> TensorBuffer``,
    #: applied by :meth:`to_host` after tensors materialize. Lets a fused
    #: region keep a decoder's math on device (argmax, box select) and
    #: delay its host-only part (label strings, overlay compose) to the
    #: sink's fetch point — so no element forces a blocking D2H mid-stream.
    finalize: Optional[Any] = None

    def __post_init__(self):
        if len(self.tensors) > NNS_TENSOR_SIZE_LIMIT:
            raise ValueError(
                f"{len(self.tensors)} tensors exceeds {NNS_TENSOR_SIZE_LIMIT}"
            )

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_arrays(cls, arrays: Sequence, pts: Optional[int] = None, **kw):
        return cls(tensors=list(arrays), pts=pts, **kw)

    @classmethod
    def wall_clock_pts(cls) -> int:
        return time.monotonic_ns()

    # -- container protocol --------------------------------------------------
    def __len__(self):
        return len(self.tensors)

    def __getitem__(self, i):
        return self.tensors[i]

    def __iter__(self):
        return iter(self.tensors)

    # -- derived -------------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def tensors_info(self) -> TensorsInfo:
        return TensorsInfo.from_arrays(self.tensors)

    def nbytes(self) -> int:
        return sum(int(np.prod(t.shape)) * t.dtype.itemsize for t in self.tensors)

    def create_stamps(self):
        """Capture timestamps carried in meta for end-to-end latency:
        the plural ``create_ts`` (aggregated/muxed frames, one stamp per
        constituent frame) or the singular ``create_t`` a source
        stamped. Returns a (possibly empty) list."""
        stamps = self.meta.get("create_ts")
        if stamps:
            return list(stamps)
        if "create_t" in self.meta:
            return [self.meta["create_t"]]
        return []

    def on_device(self) -> bool:
        return bool(self.tensors) and all(is_device_array(t) for t in self.tensors)

    # -- placement -----------------------------------------------------------
    def to_host(self) -> "TensorBuffer":
        """Materialize all tensors as numpy arrays (blocking D2H if needed),
        then apply the deferred ``finalize`` hook if one is attached."""
        out = []
        for t in self.tensors:
            out.append(np.asarray(t) if not isinstance(t, np.ndarray) else t)
        buf = self.replace(tensors=out, finalize=None)
        if self.finalize is not None:
            buf = self.finalize(buf)
        return buf

    def to_device(self, device=None, sharding=None) -> "TensorBuffer":
        """Move all tensors onto a JAX device (or sharding)."""
        import jax

        tgt = sharding if sharding is not None else device
        out = [jax.device_put(t, tgt) if tgt is not None else jax.device_put(t)
               for t in self.tensors]
        return self.replace(tensors=out)

    def pad_rows_device(self) -> "TensorBuffer":
        """Apply a deferred partial-window pad (aggregator
        ``pad-device``): zero-pad ``meta["pad_rows"]`` leading-axis rows
        onto each (device-resident) tensor with one tiny jitted program
        per (shape, pad) — the pad rows never cross the H2D link, and
        the downstream jitted consumer keeps its single full-window
        compiled shape. No-op without the meta key."""
        r = self.meta.get("pad_rows")
        if not r:
            return self
        out = [_pad_rows_fn(int(r), t.shape, str(t.dtype))(t)
               for t in self.tensors]
        meta = dict(self.meta)
        del meta["pad_rows"]
        return self.replace(tensors=out, meta=meta)

    def block_until_ready(self) -> "TensorBuffer":
        for t in self.tensors:
            if is_device_array(t):
                t.block_until_ready()
        return self

    # -- functional update ----------------------------------------------------
    def replace(self, **kw) -> "TensorBuffer":
        """Copy with replaced fields; tensors list is shallow-copied, meta is
        copied (buffers are treated as immutable once pushed)."""
        fields = dict(
            tensors=list(self.tensors),
            pts=self.pts,
            dts=self.dts,
            duration=self.duration,
            meta=dict(self.meta),
            finalize=self.finalize,
        )
        fields.update(kw)
        return TensorBuffer(**fields)

    def with_tensors(self, tensors: Sequence) -> "TensorBuffer":
        """New buffer with the same timing/meta but different payload."""
        return self.replace(tensors=list(tensors))

    def __repr__(self):
        shapes = ",".join(
            f"{tuple(t.shape)}:{np.dtype(t.dtype).name}" for t in self.tensors
        )
        dev = "dev" if self.on_device() else "host"
        return f"TensorBuffer([{shapes}] {dev} pts={self.pts})"
