"""Logging façade (reference ``nnstreamer_log.h:29-77`` ml_log* macros).

The reference maps ml_loge/logw/logi/logd onto dlog/android-log/GLib per
platform; we map onto :mod:`logging` with one namespaced logger per element
and the same severity vocabulary. Elements honor a ``silent`` property by
raising their logger's level (reference: per-element ``silent`` prop).
"""

from __future__ import annotations

import logging
import os

_ROOT = "nnstreamer_tpu"

logging.basicConfig(
    level=os.environ.get("NNSTREAMER_TPU_LOGLEVEL", "WARNING").upper(),
    format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
)


def get_logger(name: str = "") -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)
