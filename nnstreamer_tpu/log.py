"""Logging façade (reference ``nnstreamer_log.h:29-77`` ml_log* macros).

The reference maps ml_loge/logw/logi/logd onto dlog/android-log/GLib per
platform; we map onto :mod:`logging` with one namespaced logger per element
and the same severity vocabulary. Elements honor a ``silent`` property by
raising their logger's level (reference: per-element ``silent`` prop).

Configuration is lazy and idempotent: the first :func:`get_logger` call
attaches one handler to the ``nnstreamer_tpu`` package logger (level from
``NNSTREAMER_TPU_LOGLEVEL``, default WARNING) with ``propagate=False`` —
the host application's root logging config is never touched (the old
import-time ``logging.basicConfig()`` clobbered it, the classic library
anti-pattern). Call :func:`configure` to re-apply after changing the env
var or to set an explicit level programmatically.
"""

from __future__ import annotations

import logging
import os
import threading

_ROOT = "nnstreamer_tpu"
_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"

_configured = False
_config_lock = threading.Lock()


def configure(level=None, force: bool = False) -> logging.Logger:
    """Configure the package logger once (idempotent). ``level`` overrides
    ``NNSTREAMER_TPU_LOGLEVEL``; ``force=True`` re-reads the environment
    and re-applies the level even if already configured."""
    global _configured
    logger = logging.getLogger(_ROOT)
    with _config_lock:
        if _configured and not force and level is None:
            return logger
        if level is None:
            level = os.environ.get("NNSTREAMER_TPU_LOGLEVEL", "WARNING")
        if isinstance(level, str):
            level = level.upper()
        logger.setLevel(level)
        if not any(getattr(h, "_nnstpu", False) for h in logger.handlers):
            handler = logging.StreamHandler()
            handler.setFormatter(logging.Formatter(_FORMAT))
            handler._nnstpu = True  # ours: the idempotency marker
            logger.addHandler(handler)
        # our handler does the emitting; don't also bubble into the host
        # app's root handlers (double print) or its lastResort
        logger.propagate = False
        _configured = True
    return logger


def get_logger(name: str = "") -> logging.Logger:
    configure()
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)
