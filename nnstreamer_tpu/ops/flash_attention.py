"""Flash attention — tiled online-softmax attention as a Pallas TPU kernel.

Grid is (batch, heads, q_blocks, k_blocks); the TPU executes the trailing
grid axis sequentially on one core, so the running max/sum/accumulator
live in VMEM scratch across k-steps while K/V stream through VMEM one
``block_k`` tile at a time — the [seq, seq] score matrix never exists and
VMEM holds O(block) state regardless of context length. Causally-dead
k-tiles are skipped with predicated execution. bfloat16 in/out, fp32
accumulation — the MXU-friendly shape of the computation.

``flash_attention`` auto-selects: the Pallas kernel on TPU for aligned
shapes, the jnp reference otherwise (CPU tests, ragged shapes). The same
online-softmax math also runs *between* chips in
``parallel.ring.ring_attention``; this kernel is the intra-chip tile of
that scheme.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

#: scores below this act as -inf without producing exp() NaNs in fully
#: masked tiles
_NEG_BIG = -1e30

try:  # pallas import is deferred-safe: CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def attention_reference(q, k, v, causal: bool = True):
    """Plain XLA attention, [batch, seq, heads, dim] layout; fp32 softmax.

    The canonical single-device reference — parallel.ring re-exports this
    for its unsharded path.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
            causal: bool, scale: float, block_q: int, block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # a k-tile is causally dead when its first key comes after the last
    # query of this q-tile
    live = True if not causal else ki * block_k <= (qi + 1) * block_q - 1

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)               # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        m_prev = m_scr[:, :1]                              # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                interpret: bool):
    """Kernel entry on [batch, heads, seq, dim] layout."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = d ** -0.5
    grid = (b, h, sq // block_q, sk // block_k)
    kern = functools.partial(_kernel, causal=causal, scale=scale,
                             block_q=block_q, block_k=block_k)
    # batch/head/q-block axes are independent → declare them parallel so
    # the TPU distributes them instead of walking the whole grid
    # sequentially (measured 500x on a [4,512,8,64] prefill); only the
    # trailing k axis carries the online-softmax accumulator and stays
    # sequential ("arbitrary")
    semantics = ("parallel", "parallel", "parallel", "arbitrary")
    if hasattr(pltpu, "CompilerParams"):
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=semantics)
    elif hasattr(pltpu, "TPUCompilerParams"):  # older jax spelling
        compiler_params = pltpu.TPUCompilerParams(
            dimension_semantics=semantics)
    else:  # ancient jax: run without the hint (sequential grid)
        compiler_params = None
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        **({"compiler_params": compiler_params} if compiler_params
           else {}),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def _pallas_ok(q, k, block_q: int, block_k: int) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    return (sq % block_q == 0 and sk % block_k == 0 and
            block_q % 8 == 0 and block_k % 8 == 0 and
            d % 8 == 0 and d <= 256)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                    block_k: int = 256, force: str | None = None):
    """Attention on [batch, seq, heads, dim] tensors.

    ``force``: None (auto), "pallas" (kernel, interpreted off-TPU), or
    "reference".
    """
    if force == "reference":
        return attention_reference(q, k, v, causal=causal)
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    on_tpu = jax.default_backend() == "tpu"
    tileable = _HAVE_PALLAS and _pallas_ok(q, k, block_q, block_k)
    if force == "pallas":
        if not _HAVE_PALLAS:
            raise RuntimeError(
                "flash_attention: force='pallas' but jax.experimental."
                "pallas failed to import on this install")
        if not tileable:
            raise ValueError(
                f"flash_attention: shapes {q.shape}/{k.shape} not tileable "
                f"by ({block_q},{block_k})")
    elif not (on_tpu and tileable):
        return attention_reference(q, k, v, causal=causal)
    qt = q.swapaxes(1, 2)  # [b, h, s, d]
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    out = _flash_bhsd(qt, kt, vt, causal, block_q, block_k,
                      interpret=not on_tpu)
    return out.swapaxes(1, 2)
