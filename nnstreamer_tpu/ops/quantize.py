"""Int8 tensor quantization kernels — bandwidth compression for streams.

Plays the role the reference's sparse encoder plays (bandwidth saving on
tensor streams, gst/nnstreamer/elements/gsttensorsparseenc.c) for dense
activations: per-tensor absmax int8 with stochastic rounding on TPU (the
Pallas PRNG), deterministic nearest-rounding in the reference path. A
quantized frame ships 4× fewer bytes over query/pubsub transports.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from nnstreamer_tpu.ops.tiling import BLOCK_ROWS as _BLOCK_ROWS
from nnstreamer_tpu.ops.tiling import LANES as _LANES

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def _quantize_reference(x):
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf)) / 127.0
    scale = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale.reshape(1)


def dequantize_int8(q, scale):
    """int8 values + scalar scale → float32."""
    return q.astype(jnp.float32) * jnp.reshape(scale, ())


def _round_dithered(scaled, dither):
    # stochastic round to int8: uniform dither in [-0.5, 0.5) before
    # nearest-round has the same expectation as true stochastic rounding
    return jnp.clip(jnp.round(scaled + dither), -127, 127).astype(jnp.int8)


def _quant_kernel_prng(seed_ref, x_ref, scale_ref, q_ref):
    """TPU-only: dither from the on-core PRNG (no HBM dither traffic)."""
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    inv = 1.0 / scale_ref[0]
    scaled = jnp.clip(x_ref[:].astype(jnp.float32) * inv, -127.0, 127.0)
    # int32 bitcast (Mosaic has no uint32→f32 cast): uniform random int32
    # × 2⁻³² is already uniform in [-0.5, 0.5)
    bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.int32)
    dither = bits.astype(jnp.float32) * (1.0 / 4294967296.0)
    q_ref[:] = _round_dithered(scaled, dither)


def _quant_kernel_dither(x_ref, scale_ref, dither_ref, q_ref):
    """Interpret-mode variant: pltpu.prng_* has no CPU interpreter rule,
    so the dither is generated outside and streamed in."""
    inv = 1.0 / scale_ref[0]
    scaled = jnp.clip(x_ref[:].astype(jnp.float32) * inv, -127.0, 127.0)
    q_ref[:] = _round_dithered(scaled, dither_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _quantize_2d(x2, scale, seed, interpret: bool):
    rows, _ = x2.shape
    grid = (rows // _BLOCK_ROWS,)
    block = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    if interpret:
        dither = jax.random.uniform(
            jax.random.key(seed[0]), x2.shape, jnp.float32, -0.5, 0.5)
        return pl.pallas_call(
            _quant_kernel_dither,
            out_shape=jax.ShapeDtypeStruct(x2.shape, jnp.int8),
            grid=grid,
            in_specs=[block, pl.BlockSpec(memory_space=pltpu.SMEM), block],
            out_specs=block,
            interpret=True,
        )(x2, scale, dither)
    return pl.pallas_call(
        _quant_kernel_prng,
        out_shape=jax.ShapeDtypeStruct(x2.shape, jnp.int8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            block,
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=block,
    )(seed, x2, scale)


def quantize_int8(x, seed: int = 0, force: str | None = None):
    """Per-tensor absmax int8. Returns (int8 values, scale[1]).

    TPU path adds stochastic dither from the on-core PRNG so repeated
    streaming quantization doesn't bias activations; reference path is
    deterministic nearest (CPU tests stay reproducible).
    """
    if force == "pallas" and not _HAVE_PALLAS:
        raise RuntimeError("quantize_int8: force='pallas' but jax."
                           "experimental.pallas failed to import")
    on_tpu = jax.default_backend() == "tpu"
    use_pallas = _HAVE_PALLAS and (force == "pallas" or
                                   (force is None and on_tpu))
    if not use_pallas or force == "reference":
        return _quantize_reference(x)

    from nnstreamer_tpu.ops.tiling import pad_to_tiles, unpad_from_tiles

    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf)) / 127.0, 1e-30).reshape(1)
    x2, n = pad_to_tiles(xf)
    q2 = _quantize_2d(x2, scale, jnp.array([seed], jnp.int32),
                      interpret=not on_tpu)
    return unpad_from_tiles(q2, n, x.shape), scale
