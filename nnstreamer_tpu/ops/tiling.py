"""Shared lane/row tiling helpers for elementwise Pallas kernels.

TPU VPU tiles are (sublane, 128-lane); elementwise kernels here flatten
any-shape arrays to a (rows, 128) layout padded to a whole number of
kernel row-blocks, run the grid, and strip the padding.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

LANES = 128
BLOCK_ROWS = 256


def pad_to_tiles(x, dtype=None):
    """Flatten + zero-pad to (N*BLOCK_ROWS, LANES); returns (x2d, n_valid)."""
    if dtype is not None:
        x = x.astype(dtype)
    n = int(np.prod(x.shape))
    pad = (-n) % (LANES * BLOCK_ROWS)
    flat = jnp.ravel(x)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, LANES), n


def unpad_from_tiles(x2d, n_valid: int, shape):
    """Inverse of :func:`pad_to_tiles`."""
    return x2d.reshape(-1)[:n_valid].reshape(shape)
