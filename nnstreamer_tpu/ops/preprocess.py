"""Fused image preprocess — uint8 frame → normalized float, one VPU pass.

The reference does this as tensor_transform ``arithmetic``
(typecast + add + div) with orc SIMD on the host
(gst/nnstreamer/elements/gsttensortransform.c, transform-orc.orc). Here
the whole chain is one Pallas elementwise kernel: read u8, subtract mean,
multiply scale, cast — a single VMEM round trip instead of three
intermediate arrays.

(When a pipeline is region-fused, XLA already fuses the equivalent jnp
ops into the model program; this kernel serves the standalone-transform
path and odd hosts where the fusion pass is disabled.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from nnstreamer_tpu.ops.tiling import BLOCK_ROWS as _BLOCK_ROWS
from nnstreamer_tpu.ops.tiling import LANES as _LANES

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def _normalize_reference(x, mean: float, scale: float, out_dtype):
    return ((x.astype(jnp.float32) - mean) * scale).astype(out_dtype)


def _kernel(x_ref, mean_ref, scale_ref, o_ref):
    mean = mean_ref[0, 0]
    scale = scale_ref[0, 0]
    x = x_ref[:]
    if x.dtype == jnp.uint8:
        # Mosaic has no direct uint8→float32 cast; widen via int32
        x = x.astype(jnp.int32)
    o_ref[:] = ((x.astype(jnp.float32) - mean) * scale).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def _normalize_2d(x2, mean, scale, out_dtype, interpret: bool):
    rows, _ = x2.shape  # caller pads rows to a _BLOCK_ROWS multiple
    grid = (rows // _BLOCK_ROWS,)
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, mean, scale)


def normalize_u8(x, mean: float = 127.5, scale: float = 1.0 / 127.5,
                 out_dtype=jnp.bfloat16, force: str | None = None):
    """(x - mean) * scale → out_dtype, for any-shape uint8/any input.

    Auto-selects the Pallas kernel on TPU (interpret mode when forced on
    CPU), the XLA reference otherwise.
    """
    if force == "pallas" and not _HAVE_PALLAS:
        raise RuntimeError("normalize_u8: force='pallas' but jax."
                           "experimental.pallas failed to import")
    on_tpu = jax.default_backend() == "tpu"
    use_pallas = _HAVE_PALLAS and (force == "pallas" or
                                   (force is None and on_tpu))
    if not use_pallas or force == "reference":
        return _normalize_reference(x, mean, scale, out_dtype)

    from nnstreamer_tpu.ops.tiling import pad_to_tiles, unpad_from_tiles

    x2, n = pad_to_tiles(x)
    mean_s = jnp.array([[mean]], jnp.float32)
    scale_s = jnp.array([[scale]], jnp.float32)
    out2 = _normalize_2d(x2, mean_s, scale_s, jnp.dtype(out_dtype).name,
                         interpret=not on_tpu)
    return unpad_from_tiles(out2, n, x.shape)
