"""ops — TPU kernel library (Pallas) with pure-XLA reference fallbacks.

The reference reaches hand-tuned kernels through orc SIMD in
tensor_transform (gst/nnstreamer/elements/gsttensortransform.c,
transform-orc.orc) and through vendor runtimes inside tensor_filter
subplugins. Here the hot ops are Pallas TPU kernels; every op also has a
jnp reference implementation used on CPU (tests) and for odd shapes —
the EdgeTPU ``device_type:dummy`` software-fallback pattern applied at the
kernel level.
"""

from nnstreamer_tpu.ops.flash_attention import flash_attention
from nnstreamer_tpu.ops.preprocess import normalize_u8
from nnstreamer_tpu.ops.quantize import dequantize_int8, quantize_int8

__all__ = [
    "flash_attention",
    "normalize_u8",
    "quantize_int8",
    "dequantize_int8",
]
