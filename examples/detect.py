"""SSD-MobileNet detection — anchor decode + per-class NMS fused on device;
only [100, 6] box rows leave the chip per frame."""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model
from nnstreamer_tpu.models.ssd_mobilenet import ssd_mobilenet

apply_fn, params, in_info, out_info = ssd_mobilenet(image_size=300)
register_jax_model("ssd", apply_fn, params, in_info=in_info,
                   out_info=out_info)

pipe = nt.parse_launch(
    "videotestsrc num-buffers=10 width=300 height=300 pattern=gradient ! "
    "tensor_converter ! queue max-size-buffers=8 ! "
    "tensor_transform mode=arithmetic "
    "option=typecast:float32,add:-127.5,div:127.5 ! "
    "tensor_filter framework=jax model=ssd ! "
    "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
    "option4=300:300 option7=meta ! "
    "queue max-size-buffers=16 prefetch-host=true ! "
    "tensor_sink name=out to-host=true")
pipe.get("out").connect(
    lambda buf: print(f"{len(buf.meta['detections'])} detections:",
                      [(d['class'], round(d['score'], 2))
                       for d in buf.meta['detections'][:5]]))
print("run:", pipe.run(timeout=300).kind)
