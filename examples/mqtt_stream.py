"""Streaming tensors between pipelines over real MQTT.

Two pipelines connected through an MQTT broker (the in-tree conformant
MqttBroker here; point ``broker=mqtt://host:port`` at mosquitto or any
3.1.1 broker in production). Payloads carry the reference's 1KB
GstMQTTMessageHdr, so a reference mqttsrc could subscribe to the same
topic. Timestamps rebase by base-epoch difference; add
``ntp-server=pool.ntp.org`` on both elements for SNTP-corrected clocks
across hosts.

Run:  python examples/mqtt_stream.py
"""

import time

import numpy as np

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.query.mqtt import MqttBroker


def main():
    broker = MqttBroker()  # 127.0.0.1, ephemeral port
    url = f"mqtt://127.0.0.1:{broker.port}"
    print(f"broker at {url}")

    receiver = parse_launch(
        f"tensor_pubsub_src broker={url} sub_topic=demo/frames "
        "num_buffers=5 ! tensor_sink name=out"
    )
    receiver.get("out").connect(
        lambda b: print(f"received {b.tensors[0].shape} "
                        f"{b.tensors[0].dtype} pts={b.pts}"))
    receiver.start()
    time.sleep(0.3)  # let SUBSCRIBE land

    sender = parse_launch(
        "videotestsrc num-buffers=5 width=8 height=8 ! tensor_converter ! "
        f"tensor_pubsub_sink broker={url} pub_topic=demo/frames"
    )
    sender.run(timeout=60)
    receiver.wait(timeout=60)
    receiver.stop()
    broker.close()


if __name__ == "__main__":
    main()
