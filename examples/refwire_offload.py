"""Reference-wire offload — speak the NNStreamer tensor_query protocol
byte-for-byte (`wire=nnstreamer`).

The server below is reachable by an UNMODIFIED reference
tensor_query_client (tensor_query_common.c framing: i32 commands, the
176-byte TensorQueryDataInfo struct, two ports, caps-string handshake),
and our client element speaks the same wire to reference servers. The
reference wire carries no per-tensor meta, so the serversrc's `caps=`
property declares how raw memories reconstruct into typed tensors (it
is also what the APPROVE reply announces to clients).
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model

CAPS = ("other/tensors,format=static,num_tensors=1,"
        "dimensions=3:64:64:1,types=uint8")

register_jax_model("invert_u8", lambda x: (255 - x,), None)

server = nt.parse_launch(
    f"tensor_query_serversrc name=ssrc port=0 wire=nnstreamer caps={CAPS} ! "
    "tensor_filter framework=jax model=invert_u8 ! "
    "queue max-size-buffers=8 materialize-host=true ! "
    "tensor_query_serversink")
server.start()
ssrc = server.get("ssrc")  # start() is synchronous: server is bound
print(f"reference-wire server: src port {ssrc.port}, "
      f"sink (results) port {ssrc.result_port}")

client = nt.parse_launch(
    "videotestsrc num-buffers=20 width=64 height=64 ! tensor_converter ! "
    f"tensor_query_client dest-host=127.0.0.1 dest-port={ssrc.port} "
    f"sink-port={ssrc.result_port} wire=nnstreamer ! "
    "tensor_sink name=out to-host=true")
msg = client.run(timeout=60)
assert msg is not None and msg.kind == "eos", msg
out = client.get("out").buffers
print(f"{len(out)} inverted frames returned over the reference wire; "
      f"first frame dtype={out[0].tensors[0].dtype} "
      f"shape={out[0].tensors[0].shape}")
server.stop()
