"""Direct TensorFlow SavedModel ingestion: point the filter at the dir.

The reference runs TF models in-process via libtensorflow
(tensor_filter_tensorflow.cc). Here the SavedModel stages ONCE through
TF's own XLA bridge to StableHLO at open() — after that the model is an
ordinary jittable XLA callee (device-resident, fusable into pipeline
regions) and TF never runs in the hot loop.

Run:  python examples/tf_savedmodel.py   (requires tensorflow importable)
"""

import os
import tempfile

import numpy as np

from nnstreamer_tpu import parse_launch


def build_saved_model(path: str):
    import tensorflow as tf

    class Classifier(tf.Module):
        """Toy 'vision model': per-channel means as 3 class scores."""

        @tf.function(input_signature=[
            tf.TensorSpec([1, 32, 32, 3], tf.uint8)])
        def __call__(self, x):
            xf = tf.cast(x, tf.float32) / 255.0
            return {"scores": tf.reduce_mean(xf, axis=[1, 2])}

    tf.saved_model.save(Classifier(), path)
    return path


def main():
    try:
        import tensorflow  # noqa: F401
    except ImportError:
        print("tensorflow not importable — use the offline StableHLO "
              "export recipe instead (docs/model-artifacts.md)")
        return

    sm = build_saved_model(
        os.path.join(tempfile.mkdtemp(), "classifier_sm"))

    pipe = parse_launch(
        "videotestsrc num-buffers=4 width=32 height=32 pattern=smpte ! "
        "tensor_converter ! "
        f"tensor_filter framework=tensorflow model={sm} name=net ! "
        "tensor_sink name=out")
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    for i, buf in enumerate(pipe.get("out").buffers):
        scores = np.asarray(buf.tensors[0])[0]
        print(f"frame {i}: channel scores = "
              f"{np.array2string(scores, precision=3)}")
    print(f"invoke latency: {pipe.get('net').get_property('latency')} us")


if __name__ == "__main__":
    main()
