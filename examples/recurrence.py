"""Recurrence — LSTM hidden/cell state circulates through a tensor_repo
slot as device-resident arrays (never leaves HBM between steps)."""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import jax.numpy as jnp
import numpy as np

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model
from nnstreamer_tpu.models.lstm import lstm_cell

hidden = 32
apply_fn, params, _, _ = lstm_cell(input_dim=hidden, hidden=hidden)


def step(p, state):
    s = state.reshape(1, 2 * hidden).astype(jnp.float32)
    h, c = s[:, :hidden], s[:, hidden:]
    y, h2, c2 = apply_fn(p, h, h, c)
    return jnp.concatenate([h2, c2], axis=1).reshape(2 * hidden)


register_jax_model("lstm_step", step, params)

pipe = nt.parse_launch(
    "tensor_reposrc slot=state num-buffers=10 "
    f"initial-dim={2 * hidden} initial-type=float32 initial-value=0.01 "
    "timeout=10 ! "
    "tensor_filter framework=jax model=lstm_step ! "
    "tee name=t  t. ! tensor_reposink slot=state  "
    "t. ! tensor_sink name=out to-host=true")
pipe.get("out").connect(
    lambda buf: print("step norm:",
                      round(float(np.linalg.norm(np.asarray(buf[0]))), 4)))
print("run:", pipe.run(timeout=120).kind)
