"""Autoregressive LM token streaming through a tensor_repo loop.

The LSTM recurrence pattern (recurrence.py) scaled to transformer decode:
the KV cache is DEVICE-RESIDENT state circulating through a repo slot as
jax.Array handles — each pipeline iteration is one cached decode step
(models/transformer.build_decode_step), and only the sampled token ids
ever reach the host. The reference's tensor_repo enables exactly this
loop topology (tests/nnstreamer_repo_lstm); the KV-cache-in-HBM part is
what TPU adds.

Run: PYTHONPATH=.. python llm_stream.py   (CPU XLA works; TPU if available)
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import nnstreamer_tpu as nt  # noqa: E402
from nnstreamer_tpu.elements.repo import GLOBAL_REPO  # noqa: E402
from nnstreamer_tpu.filters.jax_backend import register_jax_model  # noqa: E402
import jax  # noqa: E402

from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    build_greedy_stream_step,
    build_prefill,
    init_params,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer  # noqa: E402

N_TOKENS = 16
cfg = TransformerConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=64, dtype=jnp.float32)
params = init_params(cfg)
register_jax_model("lm_decode", build_greedy_stream_step(cfg), params)

# serving flow: prefill the prompt in ONE full-sequence pass, then stream.
# The warmed cache enters the loop as a device-resident jax.Array — it
# never leaves HBM.
prompt = jnp.asarray([[7, 42, 3, 99]], jnp.int32)
logits, cache = jax.jit(build_prefill(cfg))(params, prompt)
first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
GLOBAL_REPO.set("lm", TensorBuffer(
    [np.asarray(first),
     cache,
     np.asarray(prompt.shape[1], np.int32)], pts=0))

pipe = nt.parse_launch(
    f"tensor_reposrc slot=lm num-buffers={N_TOKENS} timeout=30 ! "
    "tensor_filter framework=jax model=lm_decode name=f ! "
    "tee name=t  t. ! tensor_reposink slot=lm  "
    "t. ! tensor_sink name=out to-host=false")

tokens = []
pipe.get("out").connect(
    lambda b: tokens.append(int(np.asarray(b[0]).reshape(-1)[0])))
msg = pipe.run(timeout=300)
assert msg is not None and msg.kind == "eos", msg
print(f"prompt {prompt.tolist()[0]} → first sampled {int(first[0])}")
print(f"streamed {len(tokens)} tokens: {tokens}")
print(f"decode-step latency: {pipe.get('f').get_property('latency')} µs")
