"""Sharded invoke — the filter shards its batch dim over every visible
device with NamedSharding; XLA inserts the collectives.

Run with a virtual 8-device mesh to try it anywhere:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sharded.py
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import jax
import jax.numpy as jnp
import numpy as np

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model

n_dev = len(jax.devices())
print(f"devices: {n_dev} x {jax.devices()[0].platform}")

w = jnp.full((3, 8), 0.5, jnp.float32)
register_jax_model("lin", lambda p, x: x.astype(jnp.float32) @ p, w)

# the sharded batch dim must be divisible by the device count — push
# device-count-sized batches of frames [n_dev, H, W, 3]
pipe = nt.parse_launch(
    "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
    "tensor_filter framework=jax model=lin custom=sharding:batch ! "
    "tensor_sink name=out to-host=true")
pipe.get("out").connect(lambda buf: print("out", buf))
src = pipe.get("src")
pipe.start()
for i in range(5):
    src.push([np.full((n_dev, 8, 4, 3), i, np.uint8)])
src.end_of_stream()
msg = pipe.wait(timeout=120)
pipe.stop()
print("run:", msg.kind if msg is not None else "timeout")
