"""Sharded invoke — the filter shards its batch dim over every visible
device with NamedSharding; XLA inserts the collectives.

Run with a virtual mesh to try it anywhere:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sharded.py
"""

import os

# choose the platform BEFORE the first jax call initializes the backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

try:
    jax.devices()
except RuntimeError:
    # host preset an unusable platform (e.g. a tunnel plugin this
    # process lacks) — fall back to CPU before the backend is committed
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model

print(f"devices: {len(jax.devices())} x {jax.devices()[0].platform}")

w = jnp.full((3, 8), 0.5, jnp.float32)  # frames are [1, H, W, 3]
register_jax_model("lin", lambda p, x: x.astype(jnp.float32) @ p, w)

pipe = nt.parse_launch(
    "videotestsrc num-buffers=5 width=4 height=8 ! tensor_converter ! "
    "tensor_transform mode=typecast option=float32 ! "
    "tensor_filter framework=jax model=lin custom=sharding:batch ! "
    "tensor_sink name=out to-host=true")
pipe.get("out").connect(lambda buf: print("out", buf))
print("run:", pipe.run(timeout=120).kind)
