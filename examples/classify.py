"""MobileNetV2 classification — the flagship fused pipeline.

uint8 frame → normalize → MobileNet → argmax runs as ONE XLA program;
only the label index/score cross back per frame."""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model
from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

apply_fn, params, in_info, out_info = mobilenet_v2(image_size=224)
register_jax_model("mnv2", apply_fn, params, in_info=in_info,
                   out_info=out_info)

pipe = nt.parse_launch(
    "videotestsrc num-buffers=30 width=224 height=224 pattern=gradient ! "
    "tensor_converter ! queue max-size-buffers=8 ! "
    "tensor_transform mode=arithmetic "
    "option=typecast:float32,add:-127.5,div:127.5 ! "
    "tensor_filter framework=jax model=mnv2 name=net ! "
    "tensor_decoder mode=image_labeling ! "
    "queue max-size-buffers=32 prefetch-host=true ! "
    "tensor_sink name=out to-host=true")
pipe.get("out").connect(
    lambda buf: print(f"label={buf.meta['label']} "
                      f"score={buf.meta['score']:.3f}"))
msg = pipe.run(timeout=300)
print(f"done: {msg.kind}; invoke latency "
      f"{pipe.get('net').get_property('latency')} us")
