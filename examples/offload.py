"""Distributed offload — a client pipeline sends frames over the framed
TCP query protocol to a server pipeline; max-in-flight pipelines the
round trips."""

import numpy as np

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters import register_custom_easy
from nnstreamer_tpu.tensors.types import TensorsInfo

info = TensorsInfo.from_str("3:64:64:1", "uint8")
register_custom_easy("invert",
                     lambda ins: [255 - np.asarray(ins[0])], info, info)

server = nt.parse_launch(
    "tensor_query_serversrc name=ssrc port=0 ! "
    "tensor_filter framework=custom-easy model=invert ! "
    "tensor_query_serversink")
server.start()
port = server.get("ssrc").port
print(f"server listening on 127.0.0.1:{port}")

client = nt.parse_launch(
    "videotestsrc num-buffers=20 width=64 height=64 ! tensor_converter ! "
    f"tensor_query_client dest-host=127.0.0.1 dest-port={port} "
    "max-in-flight=8 ! tensor_sink name=out to-host=true")
client.get("out").connect(lambda buf: print("got", buf))
print("client:", client.run(timeout=120).kind)
server.stop()
