"""Latency-budget adaptive batching — bound a live stream's per-frame
latency while keeping the batched MXU dispatch.

A micro-batched pipeline (aggregator frames-out=8) makes a 30 fps
frame wait up to 267 ms for its batch window. `latency-budget-ms=50`
flushes a partial window once its oldest frame has waited 50 ms —
padded ON DEVICE to the compiled batch shape (`pad-device=true`, so
only real frames cross the host→device link) and trimmed back at the
sink. Under overload the budget yields to backpressure and the
pipeline degrades to plain batching instead of compounding a backlog.
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()

import jax.numpy as jnp

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.jax_backend import register_jax_model


def classify(x):  # [8, 64, 64, 3] → [8, 10] pseudo-logits
    xf = (x.astype(jnp.float32) - 127.5) / 127.5
    return (jnp.stack([jnp.sum(xf, axis=(1, 2, 3))] * 10, axis=1),)


register_jax_model("demo_classify8", classify, None)

pipe = nt.parse_launch(
    "videotestsrc num-buffers=90 is-live=true framerate=30/1 "
    "width=64 height=64 pattern=gradient ! tensor_converter ! "
    "tensor_aggregator frames-in=1 frames-out=8 frames-flush=8 "
    "frames-dim=3 concat=true latency-budget-ms=50 pad-device=true ! "
    "queue max-size-buffers=4 prefetch-device=true ! "
    "tensor_filter framework=jax model=demo_classify8 ! "
    "queue max-size-buffers=4 materialize-host=true ! "
    "tensor_sink name=out to-host=true")
msg = pipe.run(timeout=60)
assert msg is not None and msg.kind == "eos", msg

sink = pipe.get("out")
frames = sum(
    b.meta.get("valid_frames", b.tensors[0].shape[0]) for b in sink.buffers)
lat = sink.latency_percentiles(50, 99, skip=16)
print(f"{len(sink.buffers)} dispatches carried {frames} frames")
if lat:
    print(f"end-to-end latency p50={lat[0]:.1f} ms p99={lat[1]:.1f} ms "
          f"(full batch window would be ~267 ms at 30 fps)")
