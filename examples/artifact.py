"""Compiled-model artifacts: export once, run anywhere the chip is.

The reference's core workflow is "point tensor_filter at an opaque model
file" (any .tflite). The TPU-native artifact is StableHLO — produced by
this framework's exporter, any JAX process, torch_xla, or TF (see
docs/model-artifacts.md), and loaded by extension with framework=auto.

Run:  python examples/artifact.py
"""

import os
import tempfile

import numpy as np

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.artifact import export_model
from nnstreamer_tpu.single import SingleShot


def main():
    workdir = tempfile.mkdtemp(prefix="nnstpu_artifact_")

    # 1. author a model the usual way (a .py with get_model()) ...
    model_py = os.path.join(workdir, "edge_detect.py")
    with open(model_py, "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "from nnstreamer_tpu.tensors.types import TensorsInfo\n"
            "IN_INFO = TensorsInfo.from_str('3:8:8:1', 'float32')\n"
            "def get_model():\n"
            "    def fn(x):\n"
            "        gx = jnp.abs(jnp.diff(x, axis=2)).mean(axis=(1, 2, 3))\n"
            "        return gx\n"
            "    return fn\n"
        )

    # 2. ... export it to a self-contained artifact (weights baked in;
    # equivalently: nns-launch --export edge_detect.py edge.jaxexp)
    artifact = os.path.join(workdir, "edge.jaxexp")
    # multi-platform artifacts run on the chip in production and CPU in CI
    out_info = export_model(model_py, artifact, platforms=("tpu", "cpu"))
    print(f"exported {artifact} (outputs: {out_info})")

    # 3. the artifact is now an opaque file: any pipeline or SingleShot
    # loads it by extension, caps come from the module signature
    with SingleShot(model=artifact) as s:
        print("input info:", s.get_input_info())
        (y,) = s.invoke([np.ones((1, 8, 8, 3), np.float32)])
        print("singleshot result:", np.asarray(y))

    pipe = parse_launch(
        "videotestsrc num-buffers=4 width=8 height=8 ! tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter model={artifact} ! tensor_sink name=out"
    )
    pipe.get("out").connect(
        lambda b: print("edge energy:", float(np.asarray(b[0])[0])))
    pipe.run(timeout=120)


if __name__ == "__main__":
    main()
