"""Audio keyword-spotting pipeline: audiotestsrc → window → classify.

The audio peer of classify.py — the same converter/filter/decoder
contract over an audio stream (reference: tensor_converter audio path +
aggregator windowing).

Run: PYTHONPATH=.. python audio.py   (CPU XLA works; TPU if available)
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import nnstreamer_tpu as nt  # noqa: E402
from nnstreamer_tpu.filters.jax_backend import register_jax_model  # noqa: E402
from nnstreamer_tpu.models.audio_classifier import audio_classifier  # noqa: E402

SAMPLES = 8000  # 0.5 s window @ 16 kHz

apply_fn, params, in_info, out_info = audio_classifier(
    samples=SAMPLES, num_classes=12)
register_jax_model("kws", apply_fn, params,
                   in_info=in_info, out_info=out_info)

pipe = nt.parse_launch(
    f"audiotestsrc num-buffers=8 samplesperbuffer={SAMPLES} ! "
    f"tensor_converter frames-per-tensor={SAMPLES} ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:32768 ! "
    "tensor_filter framework=jax model=kws name=f ! "
    "tensor_decoder mode=image_labeling ! "
    "tensor_sink name=out to-host=true")

labels = []
pipe.get("out").connect(lambda b: labels.append(b.meta["label_index"]))
msg = pipe.run(timeout=300)
assert msg is not None and msg.kind == "eos", msg
print(f"classified {len(labels)} windows; labels: {labels}")
print(f"filter latency: {pipe.get('f').get_property('latency')} µs")
