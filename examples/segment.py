"""Semantic segmentation pipeline — per-pixel argmax on device.

The fused region runs normalize → encoder-decoder FCN → argmax as one
XLA program; an [H, W] int32 class map crosses to the host (C× less D2H
than raw logits), where the image_segment decoder colors it RGBA.

Run: PYTHONPATH=.. python segment.py   (CPU XLA works; TPU if available)
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import nnstreamer_tpu as nt  # noqa: E402
from nnstreamer_tpu.filters.jax_backend import register_jax_model  # noqa: E402
from nnstreamer_tpu.models.segmenter import segmenter  # noqa: E402

SIZE = 256
apply_fn, params, in_info, out_info = segmenter(num_classes=21,
                                                image_size=SIZE)


def net(p, x):
    return apply_fn(p, (x.astype(jnp.float32) - 127.5) / 127.5)


register_jax_model("seg", net, params)

pipe = nt.parse_launch(
    f"videotestsrc num-buffers=30 width={SIZE} height={SIZE} "
    "pattern=smpte ! tensor_converter ! queue max-size-buffers=8 ! "
    "tensor_filter framework=jax model=seg name=net ! "
    "tensor_decoder mode=image_segment ! "
    "queue max-size-buffers=32 prefetch-host=true ! "
    "tensor_sink name=out to-host=true")
pipe.get("out").connect(
    lambda buf: print(
        f"frame pts={buf.pts}: classes present="
        f"{sorted(np.unique(buf.meta['segment_labels']).tolist())}"))
msg = pipe.run(timeout=300)
print(f"done: {msg.kind}; invoke latency "
      f"{pipe.get('net').get_property('latency')} us")
