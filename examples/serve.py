"""Continuous-batching LM serving: N concurrent prompts share one batched
KV-cached decode program (serving/engine.py).

Run: PYTHONPATH=.. python serve.py   (CPU XLA works; TPU if available)

Contrast with examples/llm_stream.py (one stream through the tensor_repo
pipeline loop): the engine multiplexes many streams onto the same device
program — the TPU-native answer to the reference query server's
one-request-one-invoke loop (tensor_query_server.c).
"""

from nnstreamer_tpu.utils.platform import ensure_jax_platform

ensure_jax_platform()  # fall back to CPU if the preset backend is unusable

import time  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from nnstreamer_tpu.models.transformer import TransformerConfig, init_params
from nnstreamer_tpu.serving import ContinuousBatchingEngine


def main():
    cfg = TransformerConfig(vocab=4096, d_model=256, n_heads=8, n_layers=4,
                            d_ff=1024, max_seq=256, dtype=jnp.bfloat16)
    engine = ContinuousBatchingEngine(
        cfg, init_params(cfg, seed=0), max_streams=4,
        steps_per_dispatch=8, temperature=0.7, top_k=40, seed=42,
        prefix_cache=4,  # multi-turn/system-prompt KV reuse
    ).start()

    rng = np.random.default_rng(0)
    system = rng.integers(1, cfg.vocab, 16).tolist()  # shared preamble
    prompts = [system + rng.integers(1, cfg.vocab, n).tolist() for n in
               (5, 12, 30, 9, 21, 7)]
    t0 = time.monotonic()
    streams = [engine.submit(p, max_new_tokens=48) for p in prompts]
    for s in streams:
        toks = s.result(timeout=600)
        print(f"stream {s.stream_id}: prompt_len={s.prompt_len} "
              f"generated={len(toks)} ({s.finish_reason}) "
              f"first={toks[:6]}")
    dt = time.monotonic() - t0
    st = engine.stats
    util = st["active_slot_steps"] / max(1, st["slot_steps"])
    print(f"total {st['tokens_generated']} tokens in {dt:.2f}s "
          f"({st['tokens_generated'] / dt:.1f} tok/s aggregate), "
          f"{st['dispatches']} dispatches, slot utilization {util:.0%}, "
          f"prefix hits {st['prefix_hits']} "
          f"({st['prefix_tokens_reused']} prompt tokens reused)")
    engine.stop()


if __name__ == "__main__":
    main()
